"""Unified metrics + tracing subsystem tests: registry semantics under
concurrency, Prometheus exposition format, span nesting/propagation
(threads, ParameterAveragingTrainingMaster workers, serialized contexts
for worker processes), MetricsListener wiring, event log, and the
off-by-default no-op guarantees."""
import json
import re
import threading

import numpy as np
import pytest

from deeplearning4j_tpu.observability import (EventLog, MetricsListener,
                                              MetricsRegistry, SpanContext,
                                              Tracer, default_registry,
                                              render_text,
                                              set_default_registry)
from deeplearning4j_tpu.observability.registry import DEFAULT_BUCKETS


# ---------------------------------------------------------------- registry
class TestRegistry:
    def test_counter_threaded_increments_exact(self):
        reg = MetricsRegistry()
        c = reg.counter("t_ops_total", "ops", ("worker",))

        def work(w):
            child = c.labels(str(w % 2))   # two children, contended
            for _ in range(1000):
                child.inc()

        threads = [threading.Thread(target=work, args=(w,)) for w in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.labels("0").value + c.labels("1").value == 8000

    def test_histogram_bucket_boundaries_inclusive(self):
        reg = MetricsRegistry()
        h = reg.histogram("t_lat", "lat", buckets=(1.0, 2.0, 5.0))
        for v in (0.5, 1.0, 1.5, 10.0):   # 1.0 lands IN the le=1 bucket
            h.observe(v)
        child = h._unlabeled()
        cum = dict(child.cumulative_buckets())
        assert cum[1.0] == 2
        assert cum[2.0] == 3
        assert cum[5.0] == 3
        assert cum[float("inf")] == 4
        assert child.count == 4
        assert child.sum == pytest.approx(13.0)

    def test_histogram_threaded_count_exact(self):
        reg = MetricsRegistry()
        h = reg.histogram("t_lat2", "lat", buckets=DEFAULT_BUCKETS)

        def work():
            for i in range(500):
                h.observe(i * 1e-3)

        threads = [threading.Thread(target=work) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        child = h._unlabeled()
        assert child.count == 2000
        assert child.cumulative_buckets()[-1][1] == 2000

    def test_get_or_create_identity_and_mismatch(self):
        reg = MetricsRegistry()
        a = reg.counter("t_same", "x", ("l",))
        assert reg.counter("t_same", "x", ("l",)) is a
        with pytest.raises(ValueError):
            reg.gauge("t_same")
        with pytest.raises(ValueError):
            reg.counter("t_same", "x", ("other",))
        with pytest.raises(ValueError):
            reg.counter("bad name!")
        with pytest.raises(ValueError):
            reg.counter("t_lbl", "x", ("0bad",))
        h = reg.histogram("t_hist", "x", buckets=(1.0, 2.0))
        assert reg.histogram("t_hist", "x", buckets=(2.0, 1.0)) is h  # order-free
        with pytest.raises(ValueError):   # silently mixed bucket layouts
            reg.histogram("t_hist", "x", buckets=(1.0, 5.0))

    def test_counter_rejects_negative(self):
        reg = MetricsRegistry()
        with pytest.raises(ValueError):
            reg.counter("t_neg").inc(-1)

    def test_disabled_registry_is_noop(self):
        reg = MetricsRegistry(enabled=False)
        c = reg.counter("t_off")
        g = reg.gauge("t_off_g")
        h = reg.histogram("t_off_h")
        c.inc(); g.set(5); h.observe(1.0)
        assert c.value == 0 and g.value == 0
        assert h._unlabeled().count == 0
        reg.enable()
        c.inc()
        assert c.value == 1

    def test_gauge_set_inc_dec(self):
        reg = MetricsRegistry()
        g = reg.gauge("t_depth")
        g.set(3); g.inc(); g.dec(2)
        assert g.value == 2


# -------------------------------------------------------------- exposition
_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\\n]|"
    r"\\\\|\\\"|\\n)*\"(,[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\\n]|\\\\|\\\"|"
    r"\\n)*\")*\})? (NaN|[+-]Inf|-?[0-9.e+-]+)$")


class TestExposition:
    def _registry(self):
        reg = MetricsRegistry()
        reg.counter("requests_total", "HTTP requests",
                    ("route", "code")).labels("/predict", "200").inc(3)
        reg.gauge("queue_depth", "depth").set(7)
        h = reg.histogram("latency_seconds", "latency", ("route",),
                          buckets=(0.1, 1.0))
        h.labels("/predict").observe(0.05)
        h.labels("/predict").observe(2.0)
        return reg

    def test_text_format_lines_valid(self):
        text = render_text(self._registry())
        for line in text.strip().splitlines():
            if line.startswith("# HELP ") or line.startswith("# TYPE "):
                continue
            assert _SAMPLE_RE.match(line), f"invalid exposition line: {line}"
        assert "# TYPE requests_total counter" in text
        assert "# TYPE latency_seconds histogram" in text
        assert 'requests_total{code="200",route="/predict"} 3' in text
        assert 'latency_seconds_bucket{route="/predict",le="+Inf"} 2' in text
        assert 'latency_seconds_count{route="/predict"} 2' in text

    def test_text_format_deterministic(self):
        reg = self._registry()
        assert render_text(reg) == render_text(reg)

    def test_label_escaping(self):
        reg = MetricsRegistry()
        reg.counter("t_esc", "", ("path",)).labels('a"b\\c\nd').inc()
        text = render_text(reg)
        assert 'path="a\\"b\\\\c\\nd"' in text

    def test_json_snapshot_round_trips(self):
        snap = self._registry().snapshot()
        back = json.loads(json.dumps(snap))
        assert back["requests_total"]["type"] == "counter"
        s = back["latency_seconds"]["samples"][0]
        assert s["count"] == 2
        assert s["buckets"][-1] == ["+Inf", 2]


# ------------------------------------------------------------------ tracer
class TestTracer:
    def test_nesting_parent_child(self):
        t = Tracer(enabled=True, registry=MetricsRegistry())
        with t.span("outer") as outer:
            with t.span("inner") as inner:
                assert inner.trace_id == outer.trace_id
                assert inner.parent_id == outer.span_id
            assert t.current_span() is outer
        assert t.current_span() is None
        names = [s.name for s in t.finished_spans]
        assert names == ["inner", "outer"]   # children close first
        assert all(s.duration_s >= 0 for s in t.finished_spans)

    def test_span_durations_land_in_registry(self):
        reg = MetricsRegistry()
        t = Tracer(enabled=True, registry=reg)
        with t.span("phase"):
            pass
        h = reg.get("span_seconds")
        assert h is not None
        assert h.labels("phase").count == 1

    def test_cross_thread_propagation(self):
        t = Tracer(enabled=True, registry=MetricsRegistry())
        got = {}
        with t.span("master") as root:
            ctx = t.current_context()

            def worker():
                with t.attach(ctx), t.span("worker_fit") as sp:
                    got["span"] = sp

            th = threading.Thread(target=worker)
            th.start(); th.join()
        assert got["span"].trace_id == root.trace_id
        assert got["span"].parent_id == root.span_id

    def test_context_serializes_for_processes(self):
        t = Tracer(enabled=True, registry=MetricsRegistry())
        with t.span("mp.fit"):
            wire = json.dumps(t.current_context().to_dict())
        ctx = SpanContext.from_dict(json.loads(wire))
        with t.attach(ctx), t.span("mp.worker") as sp:
            assert sp.trace_id == ctx.trace_id
            assert sp.parent_id == ctx.span_id

    def test_disabled_tracer_noop(self):
        t = Tracer(enabled=False)
        with t.span("x") as sp:
            assert sp is None
        assert t.current_context() is None
        assert t.finished_spans == []
        # attach(None) composes silently
        with t.attach(None):
            pass

    def test_attributes(self):
        t = Tracer(enabled=True, registry=MetricsRegistry())
        with t.span("s", worker=3) as sp:
            sp.set_attribute("round", 1)
        s = t.finished_spans[0]
        assert s.attributes == {"worker": 3, "round": 1}

    def test_xprof_bridge_path_runs(self):
        """bridge_xprof wraps spans in jax.profiler.TraceAnnotation —
        must work (as a no-op annotation) outside an active capture."""
        t = Tracer(enabled=True, registry=MetricsRegistry(),
                   bridge_xprof=True)
        with t.span("bridged") as sp:
            assert sp is not None
        assert t.finished_spans[0].duration_s >= 0


class TestPerformanceListenerSteadyState:
    def test_first_iteration_excluded_from_rates(self):
        """Satellite: the compile-dominated first iteration only starts
        the clock; rates cover later iterations exclusively."""
        from deeplearning4j_tpu.train.listeners import PerformanceListener

        class FakeModel:
            last_batch_size = 32

        lst = PerformanceListener(frequency=1)
        lst.iteration_done(FakeModel(), 1, 0)
        assert np.isnan(lst.samples_per_sec)      # nothing reported yet
        lst.iteration_done(FakeModel(), 2, 0)
        assert lst.samples_per_sec > 0
        assert lst.batches_per_sec > 0
        # baseline starts at the FIRST hook even off-frequency
        lst2 = PerformanceListener(frequency=5)
        lst2.iteration_done(FakeModel(), 1, 0)
        assert lst2._last_iter == 1
        for i in range(2, 6):
            lst2.iteration_done(FakeModel(), i, 0)
        assert lst2.batches_per_sec > 0           # window = iterations 2-5


# --------------------------------------------------------------- event log
class TestEventLog:
    def test_write_and_read_jsonl(self, tmp_path):
        p = tmp_path / "events.jsonl"
        with EventLog(str(p)) as log:
            log.emit("train_iteration", iteration=1, score=0.5)
            log.emit("epoch_end", epoch=0)
        records = list(EventLog.read(str(p)))
        assert [r["type"] for r in records] == ["train_iteration",
                                                "epoch_end"]
        assert records[0]["iteration"] == 1
        assert all("ts" in r for r in records)

    def test_threaded_lines_stay_atomic(self, tmp_path):
        p = tmp_path / "events.jsonl"
        log = EventLog(str(p))

        def work(w):
            for i in range(100):
                log.emit("e", worker=w, i=i)

        threads = [threading.Thread(target=work, args=(w,)) for w in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        log.close()
        records = list(EventLog.read(str(p)))   # every line parses
        assert len(records) == 400

    def test_tracer_spans_to_event_log(self, tmp_path):
        p = tmp_path / "spans.jsonl"
        log = EventLog(str(p))
        t = Tracer(enabled=True, registry=MetricsRegistry(), event_log=log)
        with t.span("phase", worker=0):
            pass
        log.close()
        (rec,) = list(EventLog.read(str(p)))
        assert rec["type"] == "span" and rec["name"] == "phase"
        assert rec["attributes"] == {"worker": 0}


# ---------------------------------------------------- training integration
def _iris_net():
    from deeplearning4j_tpu.nn.conf.input_type import InputType
    from deeplearning4j_tpu.nn.conf.multi_layer import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.updaters import Adam
    from deeplearning4j_tpu.nn.layers.feedforward import (DenseLayer,
                                                          OutputLayer)
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    conf = (NeuralNetConfiguration.builder()
            .seed(7).activation("tanh").weight_init("xavier")
            .updater(Adam(learning_rate=0.02))
            .list()
            .layer(DenseLayer(n_out=8))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    return MultiLayerNetwork(conf).init()


class TestMetricsListenerTraining:
    def test_fit_records_steps_score_and_throughput(self):
        """ISSUE 2 acceptance: training with MetricsListener attached
        records step count, examples/sec, and score in the DEFAULT
        registry."""
        from deeplearning4j_tpu.data.mnist import IrisDataSetIterator
        fresh = MetricsRegistry()
        prev = set_default_registry(fresh)
        try:
            net = _iris_net()
            net.add_listeners(MetricsListener())
            it = IrisDataSetIterator(batch_size=50)
            for _ in range(3):
                it.reset()
                net.fit(it)
            reg = default_registry()
            n_iters = net.iteration
            assert reg.get("model_iterations_total").value == n_iters
            assert reg.get("training_steps_total").value == n_iters
            assert reg.get("model_score").value == pytest.approx(
                net.get_score())
            assert reg.get("model_examples_per_sec").value > 0
            assert reg.get("training_examples_per_sec").value > 0
            assert reg.get("model_grad_norm").value > 0
            # compile/steady split: exactly one compile-phase step
            h = reg.get("training_step_seconds")
            assert h.labels("compile").count == 1
            assert h.labels("steady").count == n_iters - 1
            assert reg.get("model_epochs_total").value == 3
        finally:
            set_default_registry(prev)

    def test_device_scalar_score_not_synced(self):
        """On the ParallelWrapper path the score stays a device scalar
        mid-fit; the listener must skip it (no silent host sync) unless
        force_device_sync opts in."""
        import jax.numpy as jnp
        reg = MetricsRegistry()

        class Wrapperish:
            _score = jnp.asarray(1.5)     # device scalar, not host float
            _last_grad_stats = {"global_norm": jnp.asarray(2.0)}
            last_batch_size = 16

            @staticmethod
            def get_score():
                return float(Wrapperish._score)

        lst = MetricsListener(registry=reg)
        lst.iteration_done(Wrapperish(), 1, 0)
        assert reg.get("model_score") is None or \
            reg.get("model_score").value == 0          # skipped
        assert reg.get("model_iterations_total").value == 1  # counters run
        forced = MetricsListener(registry=reg, force_device_sync=True)
        forced.iteration_done(Wrapperish(), 1, 0)
        assert reg.get("model_score").value == pytest.approx(1.5)
        assert reg.get("model_grad_norm").value == pytest.approx(2.0)

    def test_disabled_registry_training_is_silent(self):
        """The disabled path records nothing (and syncs nothing — the
        listener returns before touching the model)."""
        fresh = MetricsRegistry(enabled=False)
        prev = set_default_registry(fresh)
        try:
            net = _iris_net()
            listener = MetricsListener()
            net.add_listeners(listener)
            x = np.random.default_rng(0).standard_normal((12, 4)).astype(
                np.float32)
            y = np.eye(3, dtype=np.float32)[np.arange(12) % 3]
            net.fit(x, y, epochs=2)
            snap = fresh.snapshot()
            for name, fam in snap.items():
                for s in fam["samples"]:
                    assert s.get("value", 0) == 0 and s.get("count", 0) == 0, \
                        (name, s)
        finally:
            set_default_registry(prev)


class TestMasterSpans:
    def test_parameter_averaging_span_propagation(self):
        """Spans nest across the ParameterAveragingTrainingMaster fan-out:
        worker_fit spans share the master.fit trace and parent onto it."""
        from deeplearning4j_tpu.data.mnist import IrisDataSetIterator
        from deeplearning4j_tpu.parallel.master import (
            ParameterAveragingTrainingMaster)
        tracer = Tracer(enabled=True, registry=MetricsRegistry())
        net = _iris_net()
        master = ParameterAveragingTrainingMaster(
            num_workers=2, averaging_frequency=1, tracer=tracer)
        master.fit(net, IrisDataSetIterator(batch_size=25))
        spans = tracer.finished_spans
        by_name = {}
        for s in spans:
            by_name.setdefault(s.name, []).append(s)
        root = by_name["master.fit"][0]
        assert {"master.split", "master.broadcast", "master.worker_fit",
                "master.aggregation"} <= set(by_name)
        for s in spans:
            assert s.trace_id == root.trace_id
        workers = {s.attributes["worker"] for s in by_name["master.worker_fit"]}
        assert workers == {0, 1}
        # worker spans parent onto the master.fit root via attach(ctx)
        assert all(s.parent_id == root.span_id
                   for s in by_name["master.worker_fit"])

    def test_stats_text_deterministic_with_worker_labels(self):
        from deeplearning4j_tpu.data.mnist import IrisDataSetIterator
        from deeplearning4j_tpu.parallel.master import (
            ParameterAveragingTrainingMaster)
        net = _iris_net()
        master = ParameterAveragingTrainingMaster(num_workers=2,
                                                  averaging_frequency=1)
        master.fit(net, IrisDataSetIterator(batch_size=25))
        text = master.stats.stats_text()
        assert text == master.stats.stats_text()   # deterministic
        lines = text.splitlines()
        assert lines[0].split() == ["phase", "worker", "count", "total_s",
                                    "mean_s"]
        # per-worker fit rows present alongside the aggregate row
        fit_rows = [ln for ln in lines if ln.startswith("fit ")]
        workers = {ln.split()[1] for ln in fit_rows}
        assert {"all", "0", "1"} <= workers
        d = master.stats.as_dict()   # backward-compatible shape
        assert {"split", "broadcast", "fit", "aggregation"} <= set(d)
        for ph in d.values():
            assert set(ph) == {"count", "total_s", "mean_s"}


# ----------------------------------------------------------------- brokers
class TestBrokerMetrics:
    def test_publish_consume_counters_and_depth(self):
        from deeplearning4j_tpu.streaming.broker import LocalMessageBroker
        fresh = MetricsRegistry()
        prev = set_default_registry(fresh)
        try:
            broker = LocalMessageBroker()
            sub = broker.subscribe("topicA")
            broker.publish("topicA", b"one")
            broker.publish("topicA", b"two")
            assert fresh.get("broker_published_total") \
                        .labels("topicA").value == 2
            assert fresh.get("broker_queue_depth") \
                        .labels("topicA").value == 2
            assert sub.poll(timeout=0.1) == b"one"
            assert fresh.get("broker_consumed_total") \
                        .labels("topicA").value == 1
            assert fresh.get("broker_queue_depth") \
                        .labels("topicA").value == 1
        finally:
            set_default_registry(prev)

    def test_drop_oldest_counted(self):
        from deeplearning4j_tpu.streaming.broker import LocalMessageBroker
        fresh = MetricsRegistry()
        prev = set_default_registry(fresh)
        try:
            broker = LocalMessageBroker(max_queue=1)
            broker.subscribe("t")
            broker.publish("t", b"a")
            broker.publish("t", b"b")   # evicts "a"
            assert fresh.get("broker_dropped_total").labels("t").value == 1
        finally:
            set_default_registry(prev)
