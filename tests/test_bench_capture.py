"""Probe-bracketed capture protocol (VERDICT r4 item 4): a BENCH_SIDE row
must only publish from a healthy before+after probe bracket; exhausted
retries tag rows ``invalid`` rather than shipping degraded-window numbers."""
import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
from bench import probe_bracketed_capture  # noqa: E402


def _probes(seq):
    it = iter(seq)
    return lambda: {"healthy": next(it)}


def test_healthy_bracket_single_pass():
    calls = []
    rows = probe_bracketed_capture(
        lambda: calls.append(1) or {"metric": "m", "value": 1},
        _probes([True, True]), sleep=lambda s: None)
    assert len(calls) == 1
    assert "invalid" not in rows[0]
    assert rows[0]["tunnel_probe"]["healthy"]


def test_sick_before_probe_backs_off_without_capturing():
    calls = []
    rows = probe_bracketed_capture(
        lambda: calls.append(1) or {"metric": "m", "value": 1},
        _probes([False, True, True]), sleep=lambda s: None)
    assert len(calls) == 1          # no capture spent in the sick window
    assert "invalid" not in rows[0]


def test_mid_capture_degradation_voids_and_retries():
    calls = []
    rows = probe_bracketed_capture(
        lambda: calls.append(1) or {"metric": "m", "value": 1},
        _probes([True, False, True, True]), sleep=lambda s: None)
    assert len(calls) == 2          # first capture voided, second shipped
    assert "invalid" not in rows[0]


def test_exhausted_retries_tag_invalid():
    calls = []
    rows = probe_bracketed_capture(
        lambda: calls.append(1) or [{"metric": "m", "value": 1}],
        _probes([True, False, True, False, True, False]),
        retries=2, sleep=lambda s: None)
    assert len(calls) == 3
    assert rows[0]["invalid"] is True
    assert rows[0]["tunnel_probe"]["healthy"] is False


def test_serve_latency_ms_rows():
    """The serving-engine bench line (ISSUE 8): per-impl rows (engine vs
    per-request) at each concurrency, with p50/p99 + req/s, the engine's
    vs_per_request ratio, and a compile-counter-verified zero-recompile
    steady state.  Tiny CPU config."""
    from deeplearning4j_tpu.nn.conf.input_type import InputType
    from deeplearning4j_tpu.nn.conf.multi_layer import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.updaters import Adam
    from deeplearning4j_tpu.nn.layers.feedforward import (DenseLayer,
                                                          OutputLayer)
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.utils import benchmarks as B

    conf = (NeuralNetConfiguration.builder().seed(7)
            .updater(Adam(learning_rate=0.05)).list()
            .layer(DenseLayer(n_out=8, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(4)).build())
    net = MultiLayerNetwork(conf).init()
    rows = B.serve_latency_ms(concurrencies=(2,), n_requests=32,
                              model=net, max_batch=8)
    assert [r["metric"] for r in rows] == [
        "serve_latency_ms[per_request,c=2]", "serve_latency_ms[engine,c=2]"]
    for row in rows:
        assert row["value"] > 0 and row["p99_ms"] >= row["value"]
        assert row["requests_per_sec"] > 0
        assert row["errors"] == 0 and row["unit"] == "ms p50"
    engine_row = rows[1]
    assert engine_row["vs_per_request"] > 0
    # the warmed bucket ladder held: no steady-state XLA recompiles
    assert engine_row["steady_recompiles"] == 0
    assert engine_row["batches_dispatched"] > 0


def test_step_time_ms_rows():
    """The step-time engine bench line (ISSUE 6): auto-vs-off rows per
    (seq, dtype) with the cost-model adaptation count.  Tiny CPU config;
    injected costs make the cost model switch to a native compile
    immediately, exercising the adaptation loop end to end."""
    from deeplearning4j_tpu.utils import benchmarks as B

    rows = B.step_time_ms(seqs=(16,), dtypes=("float32",), batch=4,
                          big_mult=2, embed=32, n_layers=2, n_heads=2,
                          vocab=64, steps=2, adapt_cap=50,
                          compile_cost_s=0.01, step_cost_s=1.0)
    assert len(rows) == 1
    row = rows[0]
    assert row["metric"] == "step_time_ms[s=16,f32]"
    assert row["value"] > 0 and row["off_policy_ms"] > 0
    # vs_off is computed from the UNROUNDED timings; recomputing from
    # the rounded row fields can differ at the 3rd-decimal boundary
    assert row["vs_off"] == pytest.approx(
        row["value"] / row["off_policy_ms"], abs=2e-3)
    assert row["big_bucket"] == 8 and row["dtype"] == "float32"
    # step cost >> compile cost: the very first small step compiles its
    # own bucket, so adaptation needs at most one probe chunk
    assert 0 < row["adapt_steps"] <= 25


def test_obs_overhead_ms_row():
    """The observability-overhead bench line (ISSUE 10): row shape for
    the paired recorder+monitor on-vs-off measurement.  A tiny run keeps
    the test fast; the <2% claim itself is a steady-state property of
    the full bench.py run (target_pct documents it in the row), not
    something a 2-round CI sample could assert without flaking."""
    from deeplearning4j_tpu.utils import benchmarks as B

    row = B.obs_overhead_ms(n_batches=12, runs=2)
    assert row["metric"] == "obs_overhead_ms"
    assert row["unit"].startswith("ms/step")
    assert row["value"] > 0 and row["off_ms"] > 0
    # the paired-delta median can dip negative under host noise, but it
    # must stay a small fraction of the step itself
    assert isinstance(row["overhead_ms"], float)
    assert abs(row["overhead_ms"]) < row["value"]
    assert row["overhead_pct"] is not None
    assert row["target_pct"] == 2.0
    assert row["steps"] == 12 and row["runs"] == 2


def test_lint_time_ms_row():
    """The lint wall-time bench line (ISSUE 9): row shape + a sane
    measurement over a small path subset (the full-package budget is
    asserted in test_lint.py; here the row contract is what's tested)."""
    from pathlib import Path

    from deeplearning4j_tpu.utils import benchmarks as B
    subset = str(Path(__file__).resolve().parents[1]
                 / "deeplearning4j_tpu" / "serving")
    row = B.lint_time_ms(paths=[subset], runs=1)
    assert row["metric"] == "lint_time_ms"
    assert row["unit"].startswith("ms")
    assert row["value"] > 0
    assert row["files"] >= 3          # serving/ has engine + 2 servers
    assert row["rules"] == 32
    assert row["findings"] == 0       # the swept package stays clean
    assert row["runs"] == 1


def test_audit_time_ms_row():
    """The IR-audit bench line (ISSUE 14; diff slice ISSUE 16): row
    shape for the canonical program-set build + full graftaudit wall
    time + the budgets.json differential gate.  A name-filtered subset
    keeps the test fast (the dense + bf16 train steps — no sharded
    meshes, no generation engine); the full-set 60s acceptance budget
    is asserted in tests/test_audit.py where the whole set is built
    anyway, and the full diff gate in tests/test_audit_diff.py."""
    from deeplearning4j_tpu.utils import benchmarks as B

    row = B.audit_time_ms(include=["train_step[dense]",
                                   "train_step[bf16]"])
    assert row["metric"] == "audit_time_ms"
    assert row["unit"].startswith("ms full canonical-set")
    assert row["value"] > 0
    assert row["value"] == pytest.approx(
        row["build_ms"] + row["audit_ms"] + row["diff_ms"], abs=0.16)
    assert row["programs"] == 2
    assert row["skipped"] == []      # under-coverage must be explicit
    assert row["rules"] == 10
    assert row["findings"] == 0       # the swept canonical set is clean
    assert row["stale_budgets"] == []  # subset rows count as skipped
    assert row["budget_ms"] == 60000.0
    assert row["value"] < row["budget_ms"]


def test_decode_tokens_per_sec_rows():
    """The generation bench line (ISSUE 11): one row per mix
    (decode-heavy / prefill-heavy) with engine + naive tokens/sec, the
    vs_naive ratio, and the counter-verified zero-recompile steady
    state.  Tiny CPU config — the engine-beats-naive acceptance gate is
    asserted at the real bench scale, where the naive baseline pays 48
    full-sequence forwards per request; at this toy scale only the row
    contract and the recompile counter are stable."""
    from deeplearning4j_tpu.models import TransformerLM
    from deeplearning4j_tpu.utils import benchmarks as B

    lm = TransformerLM(vocab_size=17, seq_len=32, embed=16, n_layers=2,
                       n_heads=2).init()
    rows = B.decode_tokens_per_sec(model=lm, max_slots=2, max_seq=32,
                                   mixes=(("decode_heavy", 3, 4, 6),
                                          ("prefill_heavy", 3, 20, 3)))
    assert [r["metric"] for r in rows] == [
        "decode_tokens_per_sec[decode_heavy]",
        "decode_tokens_per_sec[prefill_heavy]",
        "decode_tokens_per_sec[slot_capacity]"]
    for row in rows[:2]:
        assert row["unit"] == "tokens/sec"
        assert row["value"] > 0 and row["naive_tokens_per_sec"] > 0
        assert row["vs_naive"] > 0
        assert row["tokens"] == row["requests"] * row["new_tokens"]
        assert row["decode_steps"] > 0
        # paged-KV sizing columns (ISSUE 19)
        assert row["cache_bytes"] > 0
        assert row["slots_per_gb"] > 0
        # the warmed two-program set held across the whole mixed run
        assert row["steady_recompiles"] == 0
    cap = rows[2]
    assert cap["unit"] == "x_dense_slots"
    # the whole 4x fleet was simultaneously resident inside the dense
    # ring's K/V byte budget with the steady program set intact
    assert cap["value"] == 4.0
    assert cap["peak_active"] == cap["paged_slots"] == 4 * cap["dense_slots"]
    assert cap["bytes_vs_dense"] <= 1.0
    assert cap["slots_per_gb"] > cap["dense_slots_per_gb"]
    assert cap["steady_recompiles"] == 0


def test_ttft_ms_rows():
    """The time-to-first-token bench line (ISSUE 19, dense ring arm
    removed in ISSUE 20): one row per arm (paged cold / paged
    shared-prefix) with p50/p99 TTFT, the shared arm's prefix-hit
    accounting, and the counter-verified zero-recompile steady state.
    Tiny CPU config — the >= 2x shared-vs-cold acceptance gate is
    asserted at the real bench scale where the shared prefix is 64 of
    72 prompt tokens; at this toy scale only the row contract, the hit
    counters, and the recompile counter are stable."""
    from deeplearning4j_tpu.models import TransformerLM
    from deeplearning4j_tpu.utils import benchmarks as B

    lm = TransformerLM(vocab_size=17, seq_len=32, embed=16, n_layers=2,
                       n_heads=2).init()
    rows = B.ttft_ms(model=lm, max_slots=2, max_seq=32, n_requests=4,
                     prefix_len=16, suffix_len=4, new_tokens=2)
    assert [r["metric"] for r in rows] == [
        "ttft_ms[paged_cold]", "ttft_ms[paged_shared]"]
    for row in rows:
        assert row["unit"] == "ms"
        assert row["value"] > 0 and row["p99_ms"] >= row["value"]
        assert row["requests"] == 4
        assert row["steady_recompiles"] == 0
    # only the shared arm re-uses registered prefix blocks: every
    # request after the first skips the shared 16-token prefix
    assert rows[0]["prefix_hits"] == 0
    assert rows[1]["prefix_hits"] == 3
    assert rows[1]["prefill_tokens_saved"] > 0
    assert rows[1]["vs_cold"] > 0


def test_serve_fleet_rows():
    """The serving-fleet bench line set (ISSUE 20): predict req/s and
    decode tokens/s rows per replica count with ``vs_one_replica``
    ratios, plus the kill-one-replica chaos row.  Tiny CPU config at 2
    replicas — the >= 3x-at-4-replicas acceptance gate is asserted at
    the real bench scale (device-paced replicas make it
    near-linear); here the row contract, the migration accounting, and
    the zero-recompile steady state are what's stable."""
    from deeplearning4j_tpu.models import TransformerLM
    from deeplearning4j_tpu.utils import benchmarks as B

    lm = TransformerLM(vocab_size=17, seq_len=32, embed=16, n_layers=2,
                       n_heads=2).init()
    # concurrency stays >= 2 full batches PER REPLICA at the widest
    # count — a replica whose queue drains between paced batches stalls
    # its pipeline and the scaling ratio with it
    rows = B.serve_fleet(replica_counts=(1, 2), lm=lm, pace_ms=4.0,
                         concurrency=16, n_requests=96, max_slots=2,
                         new_tokens=6, kill_tokens=16, max_seq=32)
    assert [r["metric"] for r in rows] == [
        "serve_fleet[predict,r=1]", "serve_fleet[predict,r=2]",
        "serve_fleet[decode,r=1]", "serve_fleet[decode,r=2]",
        "serve_fleet[recovery]"]
    for row in rows:
        assert row["value"] is not None and row["value"] > 0
        assert row["steady_recompiles"] == 0
    # scaling ratios ride every non-baseline throughput row
    assert rows[1]["vs_one_replica"] > 1.0   # paced replicas overlap
    assert rows[3]["vs_one_replica"] > 1.0
    assert rows[0]["errors"] == rows[1]["errors"] == 0
    # the chaos row: the victim's sessions moved and every stream
    # finished — shed or served, never hung (ISSUE 20 acceptance)
    chaos = rows[-1]
    assert chaos["migrated"] >= 1
    assert chaos["completed"] == chaos["sessions"]
    assert chaos["errors"] == 0


def test_elastic_reshard_ms_row():
    """The elastic-reshard bench line (ISSUE 13): row shape for the
    member-loss -> first-clean-sharded-step measurement on the survivor
    mesh.  Tiny CPU config; the window includes lease expiry, the
    aborted barrier round, eviction, and the
    restore_sharded(mesh=survivors) re-placement."""
    import jax

    from deeplearning4j_tpu.utils import benchmarks as B

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    row = B.elastic_reshard_ms(n_batches=12)
    assert row["metric"] == "elastic_reshard_ms"
    assert row["unit"].startswith("ms member loss")
    assert row["value"] is not None and row["value"] > 0
    assert row["restore_ms"] is not None and row["restore_ms"] > 0
    # the detection slice (lease expiry + boundary wait) dominates and
    # both slices sit inside the total window
    assert row["detect_ms"] is not None
    assert row["restore_ms"] < row["value"]
    assert row["dp_before"] == 4 and row["dp_after"] == 2
    assert row["world_before"] == 2 and row["world_after"] == 1
    assert row["steps"] == 12


def test_embedding_grad_exchange_ms_rows():
    """The sparse-embedding bench line (ISSUE 15): one row per
    (vocab, touched-fraction) with the densified-exchange and
    dense-all-reduce step times, the vs_dense ratio, and the
    counter-verified zero-recompile steady state.  Tiny CPU config —
    the densified-wins acceptance gate is asserted at the real bench
    scale (vocab >= 50k, where the dense path ships a multi-MB
    all-reduce per step); at toy vocab only the row contract and the
    recompile counter are stable."""
    import jax

    from deeplearning4j_tpu.utils import benchmarks as B

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    rows = B.embedding_grad_exchange_ms(vocabs=(2048,),
                                        touched_fracs=(0.1,), dim=8,
                                        batch=64, steps=2, warm=1)
    assert [r["metric"] for r in rows] == [
        "embedding_grad_exchange_ms[v=2048,t=0.1]"]
    row = rows[0]
    assert row["unit"].startswith("ms/step")
    assert row["value"] > 0 and row["dense_all_reduce_ms"] > 0
    assert row["vs_dense"] == pytest.approx(
        row["value"] / row["dense_all_reduce_ms"], abs=2e-3)
    assert row["densified_wins"] == (row["value"]
                                     < row["dense_all_reduce_ms"])
    # the exchange block is the exact static bound min(batch, vocab)
    assert row["capacity"] == 64
    assert row["touched_rows_max"] == 204   # 0.1 * 2048, the id pool
    assert row["vocab"] == 2048 and row["dp"] == 8
    # both programs compiled during warmup; the timed windows added none
    assert row["steady_recompiles"] == 0


def test_sharded_step_time_ms_row():
    """The sharded-training bench line (ISSUE 12): sharded + replicated
    step ms at a fixed global batch, the per-device param-bytes ~1/dp
    memory win, and the counter-verified single trace shared by both
    paths.  Tiny CPU config — on the 1-core rig the collectives are
    memcpy loops, so only the row contract, the bytes ratio, and the
    trace count are stable (the ms ratio is asserted at real scale)."""
    import jax

    from deeplearning4j_tpu.utils import benchmarks as B

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    row = B.sharded_step_time_ms(hidden=64, features=32, classes=8,
                                 batch=32, steps=3, warm=1,
                                 min_shard_size=0)
    assert row["metric"] == "sharded_step_time_ms"
    assert row["unit"].startswith("ms/step")
    assert row["value"] > 0 and row["replicated_ms"] > 0
    assert row["vs_replicated"] > 0
    assert row["dp"] == 8
    # the ZeRO-3 memory win: with every eligible leaf sharded, the
    # per-device bytes land well under replicated — here all four dense
    # kernels shard, so the ratio sits near 1/dp (biases replicate)
    assert row["param_bytes_per_device"] < row["replicated_param_bytes"]
    assert row["param_bytes_ratio"] <= 0.25
    assert row["global_param_bytes"] == row["replicated_param_bytes"]
    # sharding lives in the arguments, not the trace: the replicated and
    # sharded runs share ONE trace of the train step
    assert row["train_step_traces"] == 1


def test_profiler_overhead_ms_row():
    """The step-profiler overhead bench line (ISSUE 17): row shape for
    the paired stepprof on-vs-off measurement plus the fully-fenced
    attribution coverage check.  A tiny run keeps the test fast; the
    <2% claim is a steady-state property of the full bench.py run
    (target_pct documents it), but the coverage contract — phase sums
    within 5% of step wall on fenced steps — IS asserted here, since it
    is a structural property of the attribution, not a timing one."""
    from deeplearning4j_tpu.utils import benchmarks as B

    row = B.profiler_overhead_ms(n_batches=12, runs=2)
    assert row["metric"] == "profiler_overhead_ms"
    assert row["unit"].startswith("ms/step")
    assert row["value"] > 0 and row["off_ms"] > 0
    assert isinstance(row["overhead_ms"], float)
    assert abs(row["overhead_ms"]) < row["value"]
    assert row["overhead_pct"] is not None
    assert row["target_pct"] == 2.0
    assert 0.95 <= row["phase_coverage"] <= 1.05
    assert set(row["phase_share"]) == {
        "etl_wait", "h2d", "dispatch", "device", "listener", "forensics",
        "checkpoint"}
    assert row["steps"] == 12 and row["runs"] == 2


def test_dispatch_pipeline_ms_row():
    """The bounded-dispatch pipeline bench line (ISSUE 18): row shape
    for the paired depth=1-vs-windowed measurement on both arms.  A
    tiny run keeps the test fast; the >=1.3x headline claim is a
    full-bench property, but the structural guarantees — both arms
    report every depth, ratios are finite, and flipping the host-only
    depth knob never retraces — ARE asserted here."""
    from deeplearning4j_tpu.utils import benchmarks as B

    row = B.dispatch_pipeline_ms(depths=(2,), n_batches=6, runs=2)
    assert row["metric"] == "dispatch_pipeline_ms"
    assert row["unit"].startswith("ms/step")
    assert row["depths"] == [2]
    for arm in ("dispatch_bound", "compute_bound"):
        sub = row[arm]
        assert sub["depth1_ms_vs2"] > 0
        assert sub["depth2_ms"] > 0
        assert sub["speedup_depth2"] > 0
    assert row["value"] == row["dispatch_bound"]["depth2_ms"]
    # the depth knob lives host-side: two arms, two one-time compiles,
    # zero retraces across every depth flip
    assert row["train_step_traces_total"] <= 2
    assert row["steady_recompiles"] == 0
    assert row["steps"] == 6 and row["runs"] == 2


def test_env_fingerprint_on_every_row():
    """The provenance block (ISSUE 17 satellite): env_fingerprint()
    carries the host/runtime facts, is captured once per process, and
    bench.py's _stamp attaches it to every emitted row."""
    import json as _json

    from bench import _dumps, _stamp
    from deeplearning4j_tpu.utils import benchmarks as B

    env = B.env_fingerprint(refresh=True)
    assert env["cpus"] >= 1
    assert env["python"].count(".") >= 1
    assert env["jax"] and env["jaxlib"]
    assert isinstance(env["x64"], bool)
    assert isinstance(env["overrides"], dict)
    assert all(k.startswith("DL4J_TPU_") for k in env["overrides"])
    # cached: the same dict object stamps every row of a process
    assert B.env_fingerprint() is env

    row = _stamp({"metric": "m", "value": 1})
    assert row["env"] is env
    line = _json.loads(_dumps({"metric": "m2", "value": 2}))
    assert line["env"]["cpus"] == env["cpus"]
    # an explicit env on a row is never clobbered
    assert _stamp({"env": "mine"})["env"] == "mine"


def test_transformer_lm_flops_source_card_vs_analytic(tmp_path,
                                                      monkeypatch):
    """ISSUE 17 satellite: transformer_lm_step_time routes
    achieved_tflops through the committed graftaudit card when one
    exists for the program, and labels the analytic estimate as the
    fallback otherwise."""
    import json as _json

    from deeplearning4j_tpu.utils import benchmarks as B

    kw = dict(batch=2, seq=8, embed=8, n_layers=1, n_heads=2, vocab=32,
              impls=("reference",), nbatch=2, epochs=1, blocks=1)
    monkeypatch.setenv("DL4J_TPU_CARDS_DIR", str(tmp_path))
    rows = B.transformer_lm_step_time(**kw)
    # no card in the empty dir: labeled analytic fallback (the toy-size
    # analytic estimate itself rounds to ~0 TFLOP/s — the label is the
    # contract here, not the magnitude)
    assert rows[0]["flops_source"] == "analytic"

    # the card filename mirrors graftaudit's sanitize of the program name
    card = tmp_path / "transformer_lm_reference_s_8_.json"
    card.write_text(_json.dumps({"program": "transformer_lm[reference,s=8]",
                                 "flops": 1e12}))
    rows = B.transformer_lm_step_time(**kw)
    row = rows[0]
    assert row["flops_source"] == "card"
    # card flops (1 TFLOP) over the measured ms: the two sources differ
    # by orders of magnitude at this toy size, so routing is observable
    assert row["achieved_tflops"] == pytest.approx(
        1e12 / (row["value"] * 1e-3) / 1e12, rel=0.05)
