"""Probe-bracketed capture protocol (VERDICT r4 item 4): a BENCH_SIDE row
must only publish from a healthy before+after probe bracket; exhausted
retries tag rows ``invalid`` rather than shipping degraded-window numbers."""
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
from bench import probe_bracketed_capture  # noqa: E402


def _probes(seq):
    it = iter(seq)
    return lambda: {"healthy": next(it)}


def test_healthy_bracket_single_pass():
    calls = []
    rows = probe_bracketed_capture(
        lambda: calls.append(1) or {"metric": "m", "value": 1},
        _probes([True, True]), sleep=lambda s: None)
    assert len(calls) == 1
    assert "invalid" not in rows[0]
    assert rows[0]["tunnel_probe"]["healthy"]


def test_sick_before_probe_backs_off_without_capturing():
    calls = []
    rows = probe_bracketed_capture(
        lambda: calls.append(1) or {"metric": "m", "value": 1},
        _probes([False, True, True]), sleep=lambda s: None)
    assert len(calls) == 1          # no capture spent in the sick window
    assert "invalid" not in rows[0]


def test_mid_capture_degradation_voids_and_retries():
    calls = []
    rows = probe_bracketed_capture(
        lambda: calls.append(1) or {"metric": "m", "value": 1},
        _probes([True, False, True, True]), sleep=lambda s: None)
    assert len(calls) == 2          # first capture voided, second shipped
    assert "invalid" not in rows[0]


def test_exhausted_retries_tag_invalid():
    calls = []
    rows = probe_bracketed_capture(
        lambda: calls.append(1) or [{"metric": "m", "value": 1}],
        _probes([True, False, True, False, True, False]),
        retries=2, sleep=lambda s: None)
    assert len(calls) == 3
    assert rows[0]["invalid"] is True
    assert rows[0]["tunnel_probe"]["healthy"] is False
