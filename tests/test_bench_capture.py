"""Probe-bracketed capture protocol (VERDICT r4 item 4): a BENCH_SIDE row
must only publish from a healthy before+after probe bracket; exhausted
retries tag rows ``invalid`` rather than shipping degraded-window numbers."""
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
from bench import probe_bracketed_capture  # noqa: E402


def _probes(seq):
    it = iter(seq)
    return lambda: {"healthy": next(it)}


def test_healthy_bracket_single_pass():
    calls = []
    rows = probe_bracketed_capture(
        lambda: calls.append(1) or {"metric": "m", "value": 1},
        _probes([True, True]), sleep=lambda s: None)
    assert len(calls) == 1
    assert "invalid" not in rows[0]
    assert rows[0]["tunnel_probe"]["healthy"]


def test_sick_before_probe_backs_off_without_capturing():
    calls = []
    rows = probe_bracketed_capture(
        lambda: calls.append(1) or {"metric": "m", "value": 1},
        _probes([False, True, True]), sleep=lambda s: None)
    assert len(calls) == 1          # no capture spent in the sick window
    assert "invalid" not in rows[0]


def test_mid_capture_degradation_voids_and_retries():
    calls = []
    rows = probe_bracketed_capture(
        lambda: calls.append(1) or {"metric": "m", "value": 1},
        _probes([True, False, True, True]), sleep=lambda s: None)
    assert len(calls) == 2          # first capture voided, second shipped
    assert "invalid" not in rows[0]


def test_exhausted_retries_tag_invalid():
    calls = []
    rows = probe_bracketed_capture(
        lambda: calls.append(1) or [{"metric": "m", "value": 1}],
        _probes([True, False, True, False, True, False]),
        retries=2, sleep=lambda s: None)
    assert len(calls) == 3
    assert rows[0]["invalid"] is True
    assert rows[0]["tunnel_probe"]["healthy"] is False


def test_step_time_ms_rows():
    """The step-time engine bench line (ISSUE 6): auto-vs-off rows per
    (seq, dtype) with the cost-model adaptation count.  Tiny CPU config;
    injected costs make the cost model switch to a native compile
    immediately, exercising the adaptation loop end to end."""
    from deeplearning4j_tpu.utils import benchmarks as B

    rows = B.step_time_ms(seqs=(16,), dtypes=("float32",), batch=4,
                          big_mult=2, embed=32, n_layers=2, n_heads=2,
                          vocab=64, steps=2, adapt_cap=50,
                          compile_cost_s=0.01, step_cost_s=1.0)
    assert len(rows) == 1
    row = rows[0]
    assert row["metric"] == "step_time_ms[s=16,f32]"
    assert row["value"] > 0 and row["off_policy_ms"] > 0
    assert row["vs_off"] == round(row["value"] / row["off_policy_ms"], 3)
    assert row["big_bucket"] == 8 and row["dtype"] == "float32"
    # step cost >> compile cost: the very first small step compiles its
    # own bucket, so adaptation needs at most one probe chunk
    assert 0 < row["adapt_steps"] <= 25
