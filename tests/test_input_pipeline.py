"""Device-overlapped input pipeline tests (ISSUE 3): DevicePrefetchIterator
ordering/placement/shutdown, MultiprocessETLIterator determinism + error
propagation + process hygiene, pipeline metrics, and the fit()-side
device-resident fast paths."""
import multiprocessing
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.data import (AsyncShieldDataSetIterator,
                                     DevicePrefetchIterator,
                                     INDArrayDataSetIterator,
                                     MultiprocessETLIterator,
                                     build_input_pipeline)
from deeplearning4j_tpu.observability.registry import MetricsRegistry


def _arrays(n=24, feat=4, classes=3, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, feat)).astype(np.float32)
    y = np.eye(classes, dtype=np.float32)[rng.integers(0, classes, n)]
    return x, y


class _Source:
    """Module-level picklable source factory for spawn-based workers."""

    def __init__(self, n=24, feat=4, batch=6, seed=0):
        self.n, self.feat, self.batch, self.seed = n, feat, batch, seed

    def __call__(self):
        x, y = _arrays(self.n, self.feat, seed=self.seed)
        return INDArrayDataSetIterator(x, y, self.batch)


class _ScaleTransform:
    """Deterministic transform: scale + a seeded jitter so rng semantics
    ((seed, epoch, seq) per batch) are observable."""

    def __call__(self, feats, rng):
        return feats * 2.0 + rng.standard_normal(feats.shape).astype(
            feats.dtype) * 0.01

    transform = __call__


class _GrowTransform:
    """Outgrows the probe-sized slab (forces the inline fallback) by
    widening the feature axis."""

    def __call__(self, feats, rng):
        return np.concatenate([feats, feats], axis=1)

    transform = __call__


class _BoomTransform:
    def __call__(self, feats, rng):
        raise ValueError("boom-in-worker")

    transform = __call__


# ------------------------------------------------------------ device prefetch
class TestDevicePrefetch:
    def test_content_order_and_device_residency(self):
        x, y = _arrays()
        pre = DevicePrefetchIterator(INDArrayDataSetIterator(x, y, 5),
                                     depth=2)
        got = list(pre)
        assert len(got) == 5                       # 24/5 -> 4 full + tail
        assert all(isinstance(b.features, jax.Array) for b in got)
        np.testing.assert_array_equal(
            np.concatenate([np.asarray(b.features) for b in got]), x)
        np.testing.assert_array_equal(
            np.concatenate([np.asarray(b.labels) for b in got]), y)

    def test_sharded_placement_and_trim(self):
        from deeplearning4j_tpu.parallel.mesh import batch_spec, make_mesh
        mesh = make_mesh(8)
        x, y = _arrays(n=22)                       # 22 = 2x8 sharded + 6 cut
        pre = DevicePrefetchIterator(INDArrayDataSetIterator(x, y, 10),
                                     depth=2, mesh=mesh)
        got = list(pre)
        # batches of 10, 10, 2: each trimmed to a multiple of 8 -> 8, 8,
        # and the sub-shard remainder batch dropped entirely
        assert [int(b.features.shape[0]) for b in got] == [8, 8]
        for b in got:
            assert b.features.sharding.mesh == mesh
            assert b.features.sharding.spec == batch_spec(2)
            assert b.labels.sharding.spec == batch_spec(2)
        np.testing.assert_array_equal(np.asarray(got[0].features), x[:8])
        np.testing.assert_array_equal(np.asarray(got[1].features), x[10:18])

    def test_reentrancy_guard_and_reuse(self):
        x, y = _arrays()
        pre = DevicePrefetchIterator(INDArrayDataSetIterator(x, y, 6))
        it1 = iter(pre)
        next(it1)
        with pytest.raises(RuntimeError, match="already being iterated"):
            next(iter(pre))
        it1.close()
        assert len(list(pre)) == 4                 # usable again after close

    def test_producer_error_propagates(self):
        class Boom(INDArrayDataSetIterator):
            def __iter__(self):
                yield from list(super().__iter__())[:1]
                raise ValueError("source exploded")

        x, y = _arrays()
        pre = DevicePrefetchIterator(Boom(x, y, 6), depth=2)
        with pytest.raises(ValueError, match="source exploded"):
            list(pre)

    def test_refuses_async_shield(self):
        x, y = _arrays()
        shielded = AsyncShieldDataSetIterator(INDArrayDataSetIterator(x, y, 6))
        with pytest.raises(ValueError, match="AsyncShield"):
            DevicePrefetchIterator(shielded)

    def test_starvation_and_depth_metrics(self):
        class Slow(INDArrayDataSetIterator):
            def __iter__(self):
                for ds in super().__iter__():
                    time.sleep(0.03)
                    yield ds

        reg = MetricsRegistry()
        x, y = _arrays()
        pre = DevicePrefetchIterator(Slow(x, y, 6), depth=2, registry=reg)
        assert len(list(pre)) == 4
        snap = reg.snapshot()
        starved = snap["training_pipeline_starved_total"]["samples"]
        assert any(s["labels"] == {"stage": "device"} and s["value"] >= 1
                   for s in starved)
        stages = {s["labels"]["stage"]: s["count"]
                  for s in snap["training_etl_seconds"]["samples"]}
        assert stages.get("source", 0) >= 4
        assert stages.get("h2d", 0) >= 4
        assert stages.get("wait", 0) >= 4
        assert "training_pipeline_depth" in snap

    def test_threads_cleaned_up_after_early_break(self):
        x, y = _arrays(n=60)
        pre = DevicePrefetchIterator(INDArrayDataSetIterator(x, y, 6),
                                     depth=2)
        before = threading.active_count()
        it = iter(pre)
        next(it)
        it.close()
        deadline = time.time() + 5
        while threading.active_count() > before and time.time() < deadline:
            time.sleep(0.02)
        assert threading.active_count() <= before


# --------------------------------------------------------- multiprocess ETL
class TestMultiprocessETL:
    def test_deterministic_order_content_and_rng_under_slow_consumer(self):
        tf = _ScaleTransform()
        mp_it = MultiprocessETLIterator(_Source(), tf, num_workers=2)
        got = []
        for ds in mp_it:
            time.sleep(0.02)                       # slow consumer
            got.append((np.asarray(ds.features).copy(),
                        np.asarray(ds.labels).copy()))
        ref = list(_Source()())
        assert len(got) == len(ref)
        for seq, ((f, l), ds) in enumerate(zip(got, ref)):
            rng = np.random.default_rng((0, 0, seq))
            np.testing.assert_allclose(f, tf(ds.features, rng), rtol=1e-6)
            np.testing.assert_array_equal(l, ds.labels)

    def test_worker_error_propagates_with_traceback(self):
        # explicit slot_bytes skips the parent-side sizing probe (which
        # would fail fast before any worker spawns), so the error truly
        # crosses the process boundary
        mp_it = MultiprocessETLIterator(_Source(), _BoomTransform(),
                                        num_workers=2, slot_bytes=1 << 16)
        with pytest.raises(RuntimeError, match="boom-in-worker"):
            list(mp_it)
        assert multiprocessing.active_children() == []

    def test_inline_fallback_when_batch_outgrows_slab(self):
        # slab is probe-sized for the UNTRANSFORMED width because
        # slot_bytes is forced low; grown batches ride the inline path
        tf = _GrowTransform()
        mp_it = MultiprocessETLIterator(_Source(), tf, num_workers=2,
                                        slot_bytes=8)
        got = [np.asarray(ds.features).copy() for ds in mp_it]
        ref = list(_Source()())
        assert len(got) == len(ref)
        for f, ds in zip(got, ref):
            np.testing.assert_array_equal(
                f, np.concatenate([ds.features, ds.features], axis=1))

    def test_shutdown_leaves_no_processes_or_threads(self):
        mp_it = MultiprocessETLIterator(_Source(n=48), _ScaleTransform(),
                                        num_workers=2)
        it = iter(mp_it)
        next(it)
        it.close()                                 # early consumer exit
        deadline = time.time() + 10
        while multiprocessing.active_children() and time.time() < deadline:
            time.sleep(0.05)
        assert multiprocessing.active_children() == []

    def test_reentrancy_guard(self):
        mp_it = MultiprocessETLIterator(_Source(), num_workers=1)
        it1 = iter(mp_it)
        next(it1)
        try:
            with pytest.raises(RuntimeError, match="already being iterated"):
                next(iter(mp_it))
        finally:
            it1.close()

    def test_batch_reports_source_batch_size(self):
        assert MultiprocessETLIterator(_Source(batch=6)).batch() == 6


# ----------------------------------------------------------- fit integration
def _tiny_net(seed=11):
    from deeplearning4j_tpu.nn.conf.input_type import InputType
    from deeplearning4j_tpu.nn.conf.multi_layer import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.updaters import Adam
    from deeplearning4j_tpu.nn.layers.feedforward import (DenseLayer,
                                                          OutputLayer)
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater(Adam(learning_rate=0.05)).list()
            .layer(DenseLayer(n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    return MultiLayerNetwork(conf).init()


class TestFitIntegration:
    def test_fit_consumes_device_resident_batches(self):
        from deeplearning4j_tpu.observability.registry import (
            default_registry, set_default_registry)
        x, y = _arrays(n=30)
        net = _tiny_net()
        reg = MetricsRegistry()
        prev = set_default_registry(reg)
        try:
            pre = DevicePrefetchIterator(INDArrayDataSetIterator(x, y, 6),
                                         depth=2)
            net.fit(pre, epochs=2)
        finally:
            set_default_registry(prev)
        assert np.isfinite(net.get_score())
        assert net.iteration == 10
        stages = {s["labels"]["stage"]
                  for s in reg.snapshot()["training_etl_seconds"]["samples"]}
        assert "fetch" in stages                   # fit-side wait stage
        assert {"source", "h2d", "wait"} <= stages  # prefetch stages

    def test_fit_matches_host_path_exactly(self):
        """Device prefetch must be a pure transport change: same data, same
        RNG stream -> bitwise-identical params vs the host-batch path."""
        x, y = _arrays(n=24)
        a, b = _tiny_net(), _tiny_net()
        a.fit(INDArrayDataSetIterator(x, y, 6))
        b.fit(DevicePrefetchIterator(INDArrayDataSetIterator(x, y, 6),
                                     depth=2))
        for k in a.params:
            for p in a.params[k]:
                np.testing.assert_array_equal(np.asarray(a.params[k][p]),
                                              np.asarray(b.params[k][p]))

    def test_parallel_wrapper_skips_replacement_of_mesh_sharded(self):
        from deeplearning4j_tpu.parallel.mesh import make_mesh, shard_batch
        from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
        mesh = make_mesh(8)
        net = _tiny_net()
        w = ParallelWrapper(net, mesh)
        x, _ = _arrays(n=16)
        placed = shard_batch(mesh, jnp.asarray(x))
        assert w._put(placed) is placed            # no re-placement
        host = w._put(x)
        assert isinstance(host, jax.Array)
        assert host.sharding.mesh == mesh

    def test_parallel_wrapper_fit_from_device_prefetch(self):
        from deeplearning4j_tpu.parallel.mesh import make_mesh
        from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
        mesh = make_mesh(8)
        net = _tiny_net()
        w = ParallelWrapper(net, mesh)
        x, y = _arrays(n=32)
        pre = DevicePrefetchIterator(INDArrayDataSetIterator(x, y, 16),
                                     depth=2, mesh=mesh)
        w.fit(pre, epochs=2)
        assert np.isfinite(net.get_score())
        assert net.iteration == 4

    def test_build_input_pipeline_inprocess_path(self):
        # num_workers=0: transform runs on the prefetch thread
        pipe = build_input_pipeline(_Source(), _ScaleTransform(),
                                    num_workers=0, depth=2)
        got = list(pipe)
        assert len(got) == 4
        assert all(isinstance(b.features, jax.Array) for b in got)

    def test_composed_pipeline_content_under_slow_consumer(self):
        """Regression (review finding): MP-ETL slab slots recycle while
        device-prefetched batches sit in the queue; on the CPU backend
        ``device_put`` can alias an aligned slab view, so without the
        copy-out default, queued batches mutated to another batch's rows.
        A slow consumer with a deep queue maximizes slot reuse pressure —
        every batch must still carry ITS OWN rows."""
        tf = _ScaleTransform()
        pipe = build_input_pipeline(_Source(n=48, batch=6), tf,
                                    num_workers=2, depth=3)
        got = []
        for ds in pipe:
            time.sleep(0.02)                   # let producers run ahead
            got.append(np.asarray(ds.features).copy())
        ref = list(_Source(n=48, batch=6)())
        assert len(got) == len(ref)
        for seq, (f, ds) in enumerate(zip(got, ref)):
            rng = np.random.default_rng((0, 0, seq))
            np.testing.assert_allclose(f, tf(ds.features, rng), rtol=1e-6)
