"""ModelSerializer round-trip exactness, early stopping, transfer learning,
frozen layers (reference test model: regressiontest/ + earlystopping/ +
nn transfer-learning suites).
"""
import os

import numpy as np
import pytest

from deeplearning4j_tpu import (ComputationGraph, InputType,
                                MultiLayerNetwork, NeuralNetConfiguration)
from deeplearning4j_tpu.data.mnist import IrisDataSetIterator
from deeplearning4j_tpu.earlystopping import (
    DataSetLossCalculator, EarlyStoppingConfiguration, EarlyStoppingTrainer,
    InMemoryModelSaver, LocalFileModelSaver, MaxEpochsTerminationCondition,
    MaxScoreIterationTerminationCondition,
    ScoreImprovementEpochTerminationCondition)
from deeplearning4j_tpu.nn.conf.updaters import Adam, Nesterovs
from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.layers.misc import FrozenLayer
from deeplearning4j_tpu.nn.transfer_learning import (TransferLearning,
                                                     TransferLearningHelper)
from deeplearning4j_tpu.utils import model_serializer


def iris_net(updater=None, seed=42):
    conf = (NeuralNetConfiguration.builder()
            .seed(seed).updater(updater or Adam(learning_rate=0.02))
            .list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(DenseLayer(n_out=12, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    return MultiLayerNetwork(conf).init()


def _iris_batch():
    it = IrisDataSetIterator(batch_size=150)
    ds = next(iter(it))
    return np.asarray(ds.features), np.asarray(ds.labels)


# ---------------------------------------------------------------- serializer

def test_save_restore_exact_inference(tmp_path):
    net = iris_net()
    x, y = _iris_batch()
    net.fit(x, y, epochs=10)
    p = str(tmp_path / "model.zip")
    model_serializer.write_model(net, p)
    net2 = model_serializer.restore_multi_layer_network(p)
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               np.asarray(net2.output(x)), rtol=1e-7)
    assert net2.iteration == net.iteration


def test_save_restore_exact_resume(tmp_path):
    """Updater state round-trip makes resume EXACT (reference saveUpdater)."""
    x, y = _iris_batch()
    net = iris_net(updater=Nesterovs(learning_rate=0.05, momentum=0.9))
    net.fit(x, y, epochs=5)
    p = str(tmp_path / "ckpt.zip")
    model_serializer.write_model(net, p, save_updater=True)

    restored = model_serializer.restore_multi_layer_network(p)
    # continue both nets one step — must match bit-for-bit-ish (momentum
    # buffers restored; only rng for dropout could differ, none here)
    # align rng streams — as an OWNED copy: the fused-RNG train step
    # donates the key, so sharing one buffer between two nets would hand
    # the second fit a deleted buffer
    import jax.numpy as jnp
    net._rng = jnp.array(restored._rng)
    net.fit(x, y)
    restored.fit(x, y)
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               np.asarray(restored.output(x)), rtol=1e-7)


def test_save_restore_graph(tmp_path):
    from deeplearning4j_tpu.nn.conf.computation_graph import ElementWiseVertex
    g = (NeuralNetConfiguration.builder().seed(5).updater(Adam(learning_rate=0.02))
         .graph_builder().add_inputs("in")
         .add_layer("d0", DenseLayer(n_out=8, activation="tanh"), "in")
         .add_layer("d1", DenseLayer(n_out=8, activation="tanh"), "d0")
         .add_vertex("sum", ElementWiseVertex(op="add"), "d0", "d1")
         .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                       loss="mcxent"), "sum")
         .set_outputs("out").set_input_types(InputType.feed_forward(4))
         .build())
    net = ComputationGraph(g).init()
    x, y = _iris_batch()
    net.fit(x, y, epochs=3)
    p = str(tmp_path / "graph.zip")
    model_serializer.write_model(net, p)
    net2 = model_serializer.restore_computation_graph(p)
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               np.asarray(net2.output(x)), rtol=1e-7)
    # generic loader guesses the class (ModelGuesser role)
    net3 = model_serializer.restore_model(p)
    assert isinstance(net3, ComputationGraph)
    with pytest.raises(ValueError, match="not a"):
        model_serializer.restore_multi_layer_network(p)


def test_truncated_zip_raises_corrupt_model_error(tmp_path):
    """Regression (ISSUE 5): a truncated container raises a clear
    CorruptModelError naming the path, not raw zipfile/npz internals."""
    net = iris_net()
    p = str(tmp_path / "m.zip")
    model_serializer.write_model(net, p)
    blob = open(p, "rb").read()
    with open(p, "wb") as f:
        f.write(blob[:len(blob) // 2])
    with pytest.raises(model_serializer.CorruptModelError) as ei:
        model_serializer.restore_model(p)
    assert p in str(ei.value)
    assert ei.value.path == p


def test_corrupt_member_names_the_member(tmp_path):
    """A structurally-valid zip with a damaged/missing member reports
    WHICH member failed."""
    import zipfile

    net = iris_net()
    p = str(tmp_path / "m.zip")
    model_serializer.write_model(net, p)
    clipped = str(tmp_path / "clipped.zip")
    with zipfile.ZipFile(p) as src, \
            zipfile.ZipFile(clipped, "w") as dst:
        for name in src.namelist():
            if name != "params.npz":
                dst.writestr(name, src.read(name))
    with pytest.raises(model_serializer.CorruptModelError) as ei:
        model_serializer.restore_model(clipped)
    assert ei.value.member == "params.npz"
    assert "params.npz" in str(ei.value)


def test_write_model_is_atomic_on_failure(tmp_path, monkeypatch):
    """A crash mid-save must leave the previous complete container (the
    atomic temp-then-rename contract), never a truncated one."""
    net = iris_net()
    p = str(tmp_path / "m.zip")
    model_serializer.write_model(net, p)
    before = open(p, "rb").read()

    def boom(tree):
        raise RuntimeError("simulated crash mid-serialize")

    monkeypatch.setattr(model_serializer, "_tree_to_npz_bytes", boom)
    with pytest.raises(RuntimeError, match="simulated crash"):
        model_serializer.write_model(net, p)
    assert open(p, "rb").read() == before          # old save intact
    assert os.listdir(tmp_path) == ["m.zip"]       # no temp litter
    model_serializer.restore_multi_layer_network(p)


# ------------------------------------------------------------ early stopping

def test_early_stopping_max_epochs():
    net = iris_net()
    it = IrisDataSetIterator(batch_size=50)
    conf = (EarlyStoppingConfiguration.builder()
            .score_calculator(DataSetLossCalculator(IrisDataSetIterator(batch_size=150)))
            .epoch_termination_conditions(MaxEpochsTerminationCondition(8))
            .model_saver(InMemoryModelSaver())
            .build())
    result = EarlyStoppingTrainer(conf, net, it).fit()
    assert result.termination_reason == "EpochTerminationCondition"
    assert result.total_epochs == 8
    assert result.best_model is not None
    assert len(result.score_vs_epoch) == 8
    # best score should beat the first epoch's
    assert result.best_model_score <= result.score_vs_epoch[0]


def test_early_stopping_score_improvement_patience():
    net = iris_net(updater=Adam(learning_rate=0.05))
    it = IrisDataSetIterator(batch_size=150)
    conf = (EarlyStoppingConfiguration.builder()
            .score_calculator(DataSetLossCalculator(IrisDataSetIterator(batch_size=150)))
            .epoch_termination_conditions(
                MaxEpochsTerminationCondition(500),
                ScoreImprovementEpochTerminationCondition(5, 1e-4))
            .build())
    result = EarlyStoppingTrainer(conf, net, it).fit()
    assert result.total_epochs < 500  # patience fired before the cap


def test_early_stopping_divergence_guard():
    net = iris_net(updater=Adam(learning_rate=0.02))
    it = IrisDataSetIterator(batch_size=50)
    conf = (EarlyStoppingConfiguration.builder()
            .iteration_termination_conditions(
                MaxScoreIterationTerminationCondition(1e-6))  # absurdly low → fires
            .epoch_termination_conditions(MaxEpochsTerminationCondition(3))
            .build())
    result = EarlyStoppingTrainer(conf, net, it).fit()
    assert result.termination_reason == "IterationTerminationCondition"


def test_early_stopping_local_file_saver(tmp_path):
    net = iris_net()
    it = IrisDataSetIterator(batch_size=50)
    saver = LocalFileModelSaver(str(tmp_path))
    conf = (EarlyStoppingConfiguration.builder()
            .score_calculator(DataSetLossCalculator(IrisDataSetIterator(batch_size=150)))
            .epoch_termination_conditions(MaxEpochsTerminationCondition(3))
            .model_saver(saver).save_last_model()
            .build())
    EarlyStoppingTrainer(conf, net, it).fit()
    assert os.path.exists(str(tmp_path / "bestModel.zip"))
    assert os.path.exists(str(tmp_path / "latestModel.zip"))
    best = saver.get_best_model()
    x, y = _iris_batch()
    assert best.evaluate(x, y).accuracy() > 0.3


# --------------------------------------------------------- transfer learning

def test_frozen_layer_params_do_not_move():
    net = iris_net()
    x, y = _iris_batch()
    tl = (TransferLearning.Builder(net)
          .set_feature_extractor(0)
          .build())
    w0_before = np.asarray(tl.params["layer_0"]["W"]).copy()
    w1_before = np.asarray(tl.params["layer_1"]["W"]).copy()
    tl.fit(x, y, epochs=5)
    np.testing.assert_array_equal(np.asarray(tl.params["layer_0"]["W"]),
                                  w0_before)  # frozen
    assert np.abs(np.asarray(tl.params["layer_1"]["W"]) - w1_before).max() > 0


def test_transfer_learning_replace_output():
    net = iris_net()
    x, y = _iris_batch()
    net.fit(x, y, epochs=30)
    # keep features, new 5-class head
    tl = (TransferLearning.Builder(net)
          .fine_tune_configuration(updater=Adam(learning_rate=0.01))
          .set_feature_extractor(1)
          .remove_output_layer()
          .add_layer(OutputLayer(n_out=5, activation="softmax", loss="mcxent"))
          .build())
    assert tl.output(x).shape == (150, 5)
    # retained layer params are the trained ones
    np.testing.assert_allclose(np.asarray(tl.params["layer_0"]["W"]),
                               np.asarray(net.params["layer_0"]["W"]))
    y5 = np.eye(5)[np.random.default_rng(0).integers(0, 5, 150)]
    s0 = tl.score(x=x, y=y5)
    tl.fit(x, y5, epochs=20)
    assert tl.score(x=x, y=y5) < s0


def test_transfer_learning_nout_replace():
    net = iris_net()
    tl = (TransferLearning.Builder(net)
          .n_out_replace(1, 20)  # widen middle layer; output re-inits
          .build())
    x, y = _iris_batch()
    assert tl.params["layer_1"]["W"].shape[1] == 20
    assert tl.params["layer_2"]["W"].shape[0] == 20
    assert tl.output(x).shape == (150, 3)
    assert np.isfinite(tl.score(x=x, y=y))


def test_transfer_learning_helper_featurize():
    net = iris_net()
    x, y = _iris_batch()
    net.fit(x, y, epochs=10)
    frozen = (TransferLearning.Builder(net).set_feature_extractor(0).build())
    helper = TransferLearningHelper(frozen)
    feats = helper.featurize(x)
    assert np.asarray(feats).shape == (150, 16)
    w0 = np.asarray(frozen.params["layer_0"]["W"]).copy()
    helper.fit_featurized(feats, y, epochs=10)
    np.testing.assert_array_equal(np.asarray(frozen.params["layer_0"]["W"]), w0)
    assert frozen.evaluate(x, y).accuracy() > 0.5


def test_graph_transfer_learning():
    g = (NeuralNetConfiguration.builder().seed(5).updater(Adam(learning_rate=0.02))
         .graph_builder().add_inputs("in")
         .add_layer("d0", DenseLayer(n_out=8, activation="tanh"), "in")
         .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                       loss="mcxent"), "d0")
         .set_outputs("out").set_input_types(InputType.feed_forward(4))
         .build())
    net = ComputationGraph(g).init()
    x, y = _iris_batch()
    net.fit(x, y, epochs=10)
    tl = (TransferLearning.GraphBuilder(net)
          .set_feature_extractor("d0")
          .remove_vertex_and_connections("out")
          .add_layer("newout", OutputLayer(n_out=2, activation="softmax",
                                           loss="mcxent"), "d0")
          .set_outputs("newout")
          .build())
    np.testing.assert_allclose(np.asarray(tl.params["d0"]["W"]),
                               np.asarray(net.params["d0"]["W"]))
    y2 = np.eye(2)[np.random.default_rng(1).integers(0, 2, 150)]
    w_before = np.asarray(tl.params["d0"]["W"]).copy()
    tl.fit(x, y2, epochs=5)
    np.testing.assert_array_equal(np.asarray(tl.params["d0"]["W"]), w_before)
    assert tl.output(x).shape == (150, 2)


def test_frozen_layer_serde(tmp_path):
    net = iris_net()
    tl = TransferLearning.Builder(net).set_feature_extractor(0).build()
    p = str(tmp_path / "frozen.zip")
    model_serializer.write_model(tl, p)
    net2 = model_serializer.restore_multi_layer_network(p)
    assert isinstance(net2.conf.layers[0], FrozenLayer)
    x, _ = _iris_batch()
    np.testing.assert_allclose(np.asarray(tl.output(x)),
                               np.asarray(net2.output(x)), rtol=1e-7)


def test_save_restore_bidirectional(tmp_path):
    """Review regression: nested param groups (Bidirectional fwd/bwd) must
    survive the npz round-trip."""
    from deeplearning4j_tpu.nn.layers.recurrent import (Bidirectional,
                                                        LastTimeStep, LSTM)
    conf = (NeuralNetConfiguration.builder().seed(1)
            .updater(Adam(learning_rate=0.01)).list()
            .layer(Bidirectional(fwd=LSTM(n_out=6)))
            .layer(LastTimeStep(underlying=LSTM(n_out=6)))
            .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.recurrent(3, 7)).build())
    net = MultiLayerNetwork(conf).init()
    x = np.random.default_rng(0).standard_normal((4, 7, 3))
    p = str(tmp_path / "bi.zip")
    model_serializer.write_model(net, p)
    net2 = model_serializer.restore_multi_layer_network(p)
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               np.asarray(net2.output(x)), rtol=1e-7)


def test_graph_fit_dataset_batch():
    """Review regression: cg.fit(DataSet) treats it as ONE batch."""
    from deeplearning4j_tpu.data.dataset import DataSet
    g = (NeuralNetConfiguration.builder().seed(5).updater(Adam(learning_rate=0.02))
         .graph_builder().add_inputs("in")
         .add_layer("d", DenseLayer(n_out=8, activation="tanh"), "in")
         .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                       loss="mcxent"), "d")
         .set_outputs("out").set_input_types(InputType.feed_forward(4))
         .build())
    net = ComputationGraph(g).init()
    x, y = _iris_batch()
    net.fit(DataSet(x, y))
    assert np.isfinite(net.get_score())
