"""Clustering / nearest-neighbor / t-SNE tests (reference test model:
``nearestneighbor-core/src/test/.../vptree/VpTreeNodeTest.java``,
``clustering/kmeans/KMeansTest.java``, ``deeplearning4j-core`` t-SNE tests)."""
import numpy as np
import pytest

from deeplearning4j_tpu.clustering import (BarnesHutTsne, BruteForceNN,
                                           KDTree, KMeans, SPTree, Tsne,
                                           VPTree, pairwise_distance)


def _blobs(n_per=50, seed=0):
    rng = np.random.default_rng(seed)
    centers = np.array([[0.0, 0.0], [8.0, 8.0], [-8.0, 8.0]])
    pts = np.concatenate([c + rng.standard_normal((n_per, 2)) for c in centers])
    labels = np.repeat(np.arange(3), n_per)
    return pts.astype(np.float32), labels


class TestNeighbors:
    def test_brute_force_matches_numpy(self):
        rng = np.random.default_rng(1)
        pts = rng.standard_normal((100, 5)).astype(np.float32)
        q = rng.standard_normal((7, 5)).astype(np.float32)
        d, i = BruteForceNN(pts).query(q, k=3)
        ref = np.linalg.norm(q[:, None, :] - pts[None, :, :], axis=-1)
        ref_idx = np.argsort(ref, axis=1)[:, :3]
        assert np.array_equal(i, ref_idx)
        np.testing.assert_allclose(d, np.sort(ref, axis=1)[:, :3], rtol=1e-4)

    @pytest.mark.parametrize("tree_cls", [VPTree, KDTree])
    def test_trees_match_brute_force(self, tree_cls):
        rng = np.random.default_rng(2)
        pts = rng.standard_normal((200, 4))
        tree = tree_cls(pts)
        for qi in range(5):
            q = rng.standard_normal(4)
            d, i = tree.query(q, k=5)
            ref = np.linalg.norm(pts - q, axis=1)
            order = np.argsort(ref)[:5]
            np.testing.assert_allclose(d, ref[order], rtol=1e-9)
            assert set(i) == set(order)

    def test_vptree_cosine(self):
        rng = np.random.default_rng(3)
        pts = rng.standard_normal((80, 6))
        tree = VPTree(pts, metric="cosine")
        q = rng.standard_normal(6)
        d, i = tree.query(q, k=3)
        nq = q / np.linalg.norm(q)
        np_pts = pts / np.linalg.norm(pts, axis=1, keepdims=True)
        ref = 1.0 - np_pts @ nq
        assert set(i) == set(np.argsort(ref)[:3])

    def test_pairwise_metrics(self):
        rng = np.random.default_rng(4)
        a = rng.standard_normal((3, 4)).astype(np.float32)
        b = rng.standard_normal((5, 4)).astype(np.float32)
        man = np.asarray(pairwise_distance(a, b, "manhattan"))
        ref = np.sum(np.abs(a[:, None] - b[None]), axis=-1)
        np.testing.assert_allclose(man, ref, rtol=1e-5)


class TestKMeans:
    def test_recovers_blobs(self):
        pts, labels = _blobs()
        cs = KMeans(k=3, seed=5).fit(pts)
        assert cs.centers.shape == (3, 2)
        # each true cluster maps to exactly one predicted cluster
        mapping = [np.bincount(cs.assignments[labels == c], minlength=3).argmax()
                   for c in range(3)]
        assert len(set(mapping)) == 3
        acc = np.mean([np.mean(cs.assignments[labels == c] == mapping[c])
                       for c in range(3)])
        assert acc > 0.95

    def test_nearest_cluster(self):
        pts, _ = _blobs()
        cs = KMeans(k=3, seed=5).fit(pts)
        pred = cs.nearest_cluster(np.array([[8.0, 8.0]], dtype=np.float32))
        d = np.linalg.norm(cs.centers - np.array([8.0, 8.0]), axis=1)
        assert pred[0] == np.argmin(d)

    def test_cost_decreases_with_more_clusters(self):
        pts, _ = _blobs()
        c1 = KMeans(k=1, seed=0).fit(pts).cost
        c3 = KMeans(k=3, seed=0).fit(pts).cost
        assert c3 < c1


class TestSPTree:
    def test_aggregates(self):
        rng = np.random.default_rng(6)
        pts = rng.standard_normal((64, 2))
        tree = SPTree(pts)
        assert tree.root.count == 64
        np.testing.assert_allclose(tree.root.cum_center, pts.mean(0), atol=1e-9)

    def test_theta_zero_matches_exact_repulsion(self):
        rng = np.random.default_rng(7)
        pts = rng.standard_normal((40, 2))
        tree = SPTree(pts)
        i = 3
        neg, z = tree.compute_non_edge_forces(i, theta=0.0)
        diff = pts[i] - np.delete(pts, i, axis=0)
        w = 1.0 / (1.0 + np.sum(diff * diff, axis=1))
        np.testing.assert_allclose(z, w.sum(), rtol=1e-8)
        np.testing.assert_allclose(neg, (w[:, None] ** 2 * diff).sum(0), rtol=1e-8)


class TestTsne:
    def test_exact_separates_blobs(self):
        pts, labels = _blobs(n_per=30)
        y = Tsne(perplexity=10.0, max_iter=300, seed=0).fit(pts)
        assert y.shape == (90, 2)
        # embedded clusters should be separable: inter-centroid distance large
        # relative to intra-cluster spread
        cents = np.stack([y[labels == c].mean(0) for c in range(3)])
        spread = max(np.linalg.norm(y[labels == c] - cents[c], axis=1).mean()
                     for c in range(3))
        dmin = min(np.linalg.norm(cents[a] - cents[b])
                   for a in range(3) for b in range(a + 1, 3))
        assert dmin > 2.0 * spread

    def test_barnes_hut_separates_blobs(self):
        pts, labels = _blobs(n_per=20)
        y = BarnesHutTsne(theta=0.5, perplexity=8.0, max_iter=200, seed=0).fit(pts)
        assert y.shape == (60, 2)
        cents = np.stack([y[labels == c].mean(0) for c in range(3)])
        spread = max(np.linalg.norm(y[labels == c] - cents[c], axis=1).mean()
                     for c in range(3))
        dmin = min(np.linalg.norm(cents[a] - cents[b])
                   for a in range(3) for b in range(a + 1, 3))
        assert dmin > 1.5 * spread


class TestClusteringFramework:
    """Strategy/condition machinery (reference
    clustering/algorithm/BaseClusteringAlgorithm.java)."""

    def test_fixed_count_strategy_converges_on_blobs(self):
        from deeplearning4j_tpu.clustering import KMeansClustering
        pts, labels = _blobs(n_per=40)
        cs = KMeansClustering.setup(3, max_iterations=50, seed=0).apply_to(pts)
        assert cs.centers.shape == (3, 2)
        # each blob maps to exactly one cluster
        found = {tuple(np.bincount(cs.assignments[labels == c], minlength=3))
                 for c in range(3)}
        for counts in found:
            assert max(counts) == 40

    def test_convergence_condition_stops_early(self):
        from deeplearning4j_tpu.clustering import KMeansClustering
        pts, _ = _blobs(n_per=40)
        algo = KMeansClustering.setup_with_convergence(3, rate=0.01, seed=0)
        cs = algo.apply_to(pts)
        assert cs.iterations < 50
        assert algo.history.iteration_count == cs.iterations

    def test_variance_variation_condition(self):
        from deeplearning4j_tpu.clustering import (
            BaseClusteringAlgorithm, FixedClusterCountStrategy,
            VarianceVariationCondition)
        pts, _ = _blobs(n_per=30)
        strat = FixedClusterCountStrategy.setup(3)
        strat.termination_condition = \
            VarianceVariationCondition.variance_variation_less_than(0.05, 2)
        algo = BaseClusteringAlgorithm.setup(strat, seed=1)
        cs = algo.apply_to(pts)
        assert cs.centers.shape[0] == 3
        assert cs.iterations <= algo.max_iterations

    def test_optimisation_strategy_splits_spread_clusters(self):
        from deeplearning4j_tpu.clustering import (
            BaseClusteringAlgorithm, ClusteringOptimizationType,
            OptimisationStrategy)
        pts, _ = _blobs(n_per=40)  # 3 well-separated blobs
        # start with k=1; max point-to-center threshold forces splits
        strat = (OptimisationStrategy.setup(1)
                 .optimize(ClusteringOptimizationType
                           .MINIMIZE_MAXIMUM_POINT_TO_CENTER_DISTANCE, 6.0))
        strat.end_when_distribution_variation_rate_less_than(0.001)
        algo = BaseClusteringAlgorithm.setup(strat, seed=0, max_iterations=30)
        cs = algo.apply_to(pts)
        assert cs.centers.shape[0] >= 3  # split its way up from one cluster
        info = algo.history.most_recent().cluster_set_info
        assert (info.max_distance[info.counts > 0] <= 6.5).all()

    def test_point_count_optimization(self):
        from deeplearning4j_tpu.clustering import (
            BaseClusteringAlgorithm, ClusteringOptimizationType,
            OptimisationStrategy)
        pts, _ = _blobs(n_per=40)
        strat = (OptimisationStrategy.setup(2)
                 .optimize(ClusteringOptimizationType
                           .MINIMIZE_PER_CLUSTER_POINT_COUNT, 50))
        strat.end_when_iteration_count_equals(25)
        cs = BaseClusteringAlgorithm.setup(strat, seed=0,
                                           max_iterations=25).apply_to(pts)
        assert cs.centers.shape[0] > 2

    def test_empty_cluster_reseed_and_duplicate_points(self):
        """Regression: reseeding writes into a copied buffer (device arrays
        are read-only) and k-means++ handles duplicate-heavy data."""
        from deeplearning4j_tpu.clustering import KMeansClustering
        rng = np.random.default_rng(0)
        # two tight far-apart blobs, k=8 -> empty clusters guaranteed
        pts = np.concatenate([np.zeros((20, 2)), np.full((20, 2), 50.0)])
        pts += rng.standard_normal(pts.shape) * 0.01
        cs = KMeansClustering.setup(8, max_iterations=12, seed=0).apply_to(
            pts.astype(np.float32))
        assert cs.centers.shape == (8, 2)
        # only 2 distinct values, k=6 -> zero residual distances during init
        dup = np.repeat(np.array([[0.0, 0.0], [9.0, 9.0]], np.float32),
                        20, axis=0)
        cs2 = KMeansClustering.setup(6, max_iterations=8, seed=1).apply_to(dup)
        assert cs2.centers.shape[0] == 6
