"""graftlint: per-rule positive/negative fixtures + the tier-1 gate that
keeps ``deeplearning4j_tpu/`` clean modulo the checked-in baseline.

Every rule JX001–JX017 has at least one fixture that MUST fire and one
that MUST stay silent; the gate test makes every future PR re-lint the
whole package without separate CI wiring.
"""
import json
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

REPO_ROOT = Path(__file__).resolve().parents[1]
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))

from tools.graftlint import (Baseline, RULE_DOCS, RULES,  # noqa: E402
                             lint_paths, lint_source)

PKG = REPO_ROOT / "deeplearning4j_tpu"
BASELINE = REPO_ROOT / "tools" / "graftlint" / "baseline.json"


def rules_of(src: str):
    return {f.rule for f in lint_source(textwrap.dedent(src), "fix.py")}


def findings(src: str, select=None):
    return lint_source(textwrap.dedent(src), "fix.py", select=select)


# ---------------------------------------------------------------- JX001
def test_jx001_positive_numpy_on_traced_value():
    assert "JX001" in rules_of("""
        import jax
        import numpy as np

        @jax.jit
        def f(x):
            return np.log(x)
    """)


def test_jx001_positive_jit_call_form():
    assert "JX001" in rules_of("""
        import jax
        import numpy as np

        def f(x):
            return np.tanh(x * 2)

        g = jax.jit(f)
    """)


def test_jx001_negative_host_constant_and_unjitted():
    assert "JX001" not in rules_of("""
        import jax
        import jax.numpy as jnp
        import numpy as np

        @jax.jit
        def f(x):
            scale = np.log(2.0)      # host constant: runs once at trace
            return jnp.log(x) * scale

        def g(x):
            return np.log(x)         # not a jit scope
    """)


# ---------------------------------------------------------------- JX002
def test_jx002_positive_if_on_tracer():
    assert "JX002" in rules_of("""
        import jax

        @jax.jit
        def f(x):
            if x > 0:
                return x
            return -x
    """)


def test_jx002_positive_while_on_derived_value():
    assert "JX002" in rules_of("""
        import jax

        @jax.jit
        def f(x):
            y = x * 2
            while y < 10:
                y = y + 1
            return y
    """)


def test_jx002_negative_static_arg_and_shape():
    assert "JX002" not in rules_of("""
        import functools
        import jax

        @functools.partial(jax.jit, static_argnames=("mode",))
        def f(x, mode):
            if mode == "fast":          # static arg: concrete at trace
                return x
            if x.shape[0] > 1:          # shape is trace-static
                return x + 1
            if len(x) > 2:              # len() is static too
                return x + 2
            return x
    """)


def test_jx002_negative_static_argnums_positional():
    assert "JX002" not in rules_of("""
        import jax

        def f(x, k):
            if k > 2:
                return x * k
            return x

        g = jax.jit(f, static_argnums=(1,))
    """)


# ---------------------------------------------------------------- JX003
def test_jx003_positive_float_and_item_in_fit_loop():
    got = findings("""
        import jax

        def fit(model, batches, step):
            for b in batches:
                loss = step(b)
                model.score = float(loss)
                model.last = loss.item()
    """, select=["JX003"])
    assert len(got) == 2


def test_jx003_negative_shape_reads_and_after_loop():
    assert "JX003" not in rules_of("""
        import jax
        import numpy as np

        def fit(model, batches, step):
            loss = None
            for b in batches:
                n = int(b.shape[0])            # static metadata
                m = int(getattr(b, "shape", (0,))[0])
                idx = np.array([i for i in range(n)])  # host ETL
                loss = step(b)
            model.score = float(loss)          # one sync after the loop
    """)


def test_jx003_negative_not_a_training_function():
    assert "JX003" not in rules_of("""
        import jax

        def report(values):
            out = []
            for v in values:
                out.append(float(v))
            return out
    """)


def test_jx003_negative_module_without_jax():
    assert "JX003" not in rules_of("""
        def fit(model, batches):
            for b in batches:
                model.score = float(b)
    """)


# ---------------------------------------------------------------- JX004
def test_jx004_positive_jit_in_loop():
    assert "JX004" in rules_of("""
        import jax

        def run(fs, x):
            outs = []
            for f in fs:
                outs.append(jax.jit(f)(x))
            return outs
    """)


def test_jx004_positive_immediate_invocation():
    assert "JX004" in rules_of("""
        import jax

        def once(f, x):
            return jax.jit(f)(x)
    """)


def test_jx004_negative_hoisted_jit():
    assert "JX004" not in rules_of("""
        import jax

        def make_step(f):
            step = jax.jit(f)
            def run(xs):
                return [step(x) for x in xs]
            return run
    """)


# ---------------------------------------------------------------- JX005
def test_jx005_positive_list_static_argnums():
    assert "JX005" in rules_of("""
        import jax

        def f(x, k):
            return x * k

        g = jax.jit(f, static_argnums=[1])
    """)


def test_jx005_negative_tuple_static_argnums():
    assert "JX005" not in rules_of("""
        import jax

        def f(x, k):
            return x * k

        g = jax.jit(f, static_argnums=(1,))
        h = jax.jit(f, static_argnames=("k",))
    """)


# ---------------------------------------------------------------- JX006
def test_jx006_positive_self_mutation():
    assert "JX006" in rules_of("""
        import jax

        class Model:
            @jax.jit
            def step(self, x):
                self.calls = self.calls + 1
                return x * 2
    """)


def test_jx006_positive_global_mutation():
    assert "JX006" in rules_of("""
        import jax

        COUNT = 0

        @jax.jit
        def f(x):
            global COUNT
            COUNT += 1
            return x
    """)


def test_jx006_negative_local_state_and_unjitted():
    assert "JX006" not in rules_of("""
        import jax

        class Model:
            @jax.jit
            def step(self, x):
                y = x * 2          # locals are fine
                return y

            def host_update(self):
                self.calls = 1     # not traced: fine
    """)


# ---------------------------------------------------------------- JX007
def test_jx007_positive_bare_except():
    assert "JX007" in rules_of("""
        def f():
            try:
                return 1
            except:
                return 2
    """)


def test_jx007_negative_typed_except():
    assert "JX007" not in rules_of("""
        def f():
            try:
                return 1
            except Exception:
                return 2
            except (ValueError, OSError):
                return 3
    """)


# ---------------------------------------------------------------- JX008
def test_jx008_positive_mutable_defaults():
    got = findings("""
        def f(a, xs=[], m={}):
            return a

        def g(b, s=set()):
            return b
    """, select=["JX008"])
    assert len(got) == 3


def test_jx008_negative_none_and_immutable_defaults():
    assert "JX008" not in rules_of("""
        def f(a, xs=None, t=(), name="x", n=3):
            xs = [] if xs is None else xs
            return a
    """)


# ---------------------------------------------------------------- JX009
def test_jx009_positive_unsynced_timing():
    assert "JX009" in rules_of("""
        import time
        import jax.numpy as jnp

        def bench(f, x):
            t0 = time.perf_counter()
            y = f(x) + jnp.ones(3)
            return time.perf_counter() - t0
    """)


def test_jx009_negative_block_until_ready():
    assert "JX009" not in rules_of("""
        import time
        import jax
        import jax.numpy as jnp

        def bench(f, x):
            t0 = time.perf_counter()
            y = f(x) + jnp.ones(3)
            jax.block_until_ready(y)
            return time.perf_counter() - t0
    """)


def test_jx009_negative_fetch_closed_and_deadlines():
    assert "JX009" not in rules_of("""
        import time
        import numpy as np
        import jax.numpy as jnp

        def bench(f, x):
            t0 = time.perf_counter()
            y = float(np.asarray(f(x))[0])   # fetch closes the async gap
            return time.perf_counter() - t0

        def poll(q, timeout):
            deadline = time.time() + timeout   # deadline, not measurement
            while time.time() < deadline:
                v = q.get()
                if v is not None:
                    return v * jnp.ones(1)
    """)


# ---------------------------------------------------------------- JX010
def test_jx010_positive_float64_astype():
    assert "JX010" in rules_of("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            return x.astype(jnp.float64)
    """)


def test_jx010_positive_dtype_string():
    assert "JX010" in rules_of("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            return jnp.zeros_like(x, dtype="float64")
    """)


def test_jx010_negative_float32_and_outside_jit():
    assert "JX010" not in rules_of("""
        import jax
        import jax.numpy as jnp
        import numpy as np

        @jax.jit
        def f(x):
            return x.astype(jnp.float32)

        def host(x):
            return np.float64(x)   # host-side double is fine
    """)


# ---------------------------------------------------------------- JX011
def test_jx011_positive_interval_subtraction():
    assert "JX011" in rules_of("""
        import time

        def measure(f):
            t0 = time.time()
            f()
            return time.time() - t0
    """)


def test_jx011_positive_propagated_sample_and_bare_import():
    # one-hop propagation (now -> self._last) across methods, with
    # `from time import time`
    assert "JX011" in rules_of("""
        from time import time

        class Listener:
            def start(self):
                now = time()
                self._last = now

            def rate(self, n):
                now = time()
                return n / (now - self._last)
    """)


def test_jx011_negative_deadline_idiom_and_timestamps():
    assert "JX011" not in rules_of("""
        import time

        def wait(poll, timeout):
            deadline = time.time() + timeout
            while time.time() < deadline:
                remaining = deadline - time.time()  # remaining, not elapsed
                poll(remaining)

        def stamp(record):
            record["ts"] = time.time()   # timestamp: no arithmetic
            return record
    """)


def test_jx011_negative_perf_counter_interval():
    assert "JX011" not in rules_of("""
        import time

        def measure(f):
            t0 = time.perf_counter()
            f()
            return time.perf_counter() - t0
    """)


# ---------------------------------------------------------------- JX012
def test_jx012_positive_device_put_in_loop():
    assert "JX012" in rules_of("""
        import jax

        def feed(step, batches):
            for b in batches:
                step(jax.device_put(b))
    """)


def test_jx012_positive_bare_device_put_in_while():
    assert "JX012" in rules_of("""
        from jax import device_put

        def feed(step, batches):
            while batches:
                step(device_put(batches.pop()))
    """)


def test_jx012_positive_asarray_of_device_value_in_loop():
    assert "JX012" in rules_of("""
        import jax.numpy as jnp
        import numpy as np

        def collect(xs):
            d = jnp.asarray(xs)
            out = []
            for i in range(10):
                out.append(np.asarray(d))   # D2H fetch every iteration
            return out
    """)


def test_jx012_negative_hoisted_and_host_and_jit():
    assert "JX012" not in rules_of("""
        import jax
        import jax.numpy as jnp
        import numpy as np

        def place(b):
            return jax.device_put(b)        # no loop: a prefetch stage

        def loop(items):
            total = 0.0
            for it in items:
                a = np.asarray(it)          # host list -> host array
                total += a.sum()
            return total

        @jax.jit
        def f(x):
            for i in range(3):              # unrolled at trace time
                x = jax.device_put(x)
            return x
    """)


def test_jx012_pragma_suppresses():
    assert "JX012" not in rules_of("""
        import jax

        def prefetch(batches):
            for b in batches:
                yield jax.device_put(b)  # graftlint: disable=JX012  (the prefetch stage itself)
    """)


# ---------------------------------------------------------------- JX013
def test_jx013_positive_method_local_jit_closes_over_self():
    assert "JX013" in rules_of("""
        import jax

        class Net:
            def make_step(self):
                def step(params, x):
                    return params * self.scale + x
                return jax.jit(step)
    """)


def test_jx013_positive_decorated_def_inside_method():
    assert "JX013" in rules_of("""
        import jax

        class Net:
            def fit(self, x):
                @jax.jit
                def step(p):
                    return self.forward(p, x)
                return step(self.params)
    """)


def test_jx013_positive_lambda_argument():
    assert "JX013" in rules_of("""
        import jax

        class Net:
            def make(self):
                return jax.jit(lambda x: x * self.scale)
    """)


def test_jx013_negative_self_free_closure_and_module_level():
    assert "JX013" not in rules_of("""
        import jax

        def build_step(conf, tx):
            def step(params, x):
                return params * conf.scale + tx(x)
            return jax.jit(step)

        class Net:
            def make_step(self):
                conf = self.conf
                def step(params, x):       # closes over conf, NOT self
                    return params * conf.scale + x
                return jax.jit(step)
    """)


def test_jx013_negative_jit_outside_methods():
    assert "JX013" not in rules_of("""
        import jax

        def helper(f):
            def step(x):
                return f(x)
            return jax.jit(step)
    """)


# ---------------------------------------------------------------- JX014
def test_jx014_positive_zipfile_write_to_checkpoint_path():
    assert "JX014" in rules_of("""
        import os
        import zipfile

        def save(d, tag, payload):
            path = os.path.join(d, f"checkpoint_{tag}.zip")
            with zipfile.ZipFile(path, "w") as zf:
                zf.writestr("a", payload)
    """)


def test_jx014_positive_open_wb_on_ckpt_name_and_savez_model_zip():
    got = rules_of("""
        import numpy as np

        def save(d, data, arrs, ckpt_file):
            with open(ckpt_file, "wb") as f:
                f.write(data)
            np.savez(d + "/bestModel.zip", **arrs)
    """)
    assert "JX014" in got


def test_jx014_positive_one_hop_alias():
    assert "JX014" in rules_of("""
        import os

        def save(d, data):
            path = os.path.join(d, "ckpt-00000001.bin")
            dst = path
            with open(dst, "wb") as f:
                f.write(data)
    """)


def test_jx014_negative_atomic_helper_reads_and_plain_paths():
    assert "JX014" not in rules_of("""
        import io
        import zipfile
        import numpy as np
        from deeplearning4j_tpu.faulttolerance.atomic import atomic_file

        def save(dst, arrs, log_path, ckpt_path, shard_path, data):
            with atomic_file(dst) as tmp:          # helper: tmp is runtime
                with zipfile.ZipFile(tmp, "w") as zf:
                    zf.writestr("a", b"x")
            buf = io.BytesIO()
            np.savez(buf, **arrs)                  # in-memory buffer
            with open(log_path, "wb") as f:        # not checkpoint-like
                f.write(data)
            with zipfile.ZipFile(ckpt_path, "r") as zf:    # read-only
                zf.namelist()
            np.savez(shard_path, **arrs)           # not checkpoint-like
            with open(ckpt_path, "w") as f:        # text mode: manifest
                f.write("{}")                      # writers go via helper,
                                                   # but rule targets "wb"
    """)


def test_jx014_negative_same_name_in_unrelated_function():
    # name taint is per-scope: `path` holding a checkpoint name in one
    # function must not flag an unrelated `path` written elsewhere
    assert "JX014" not in rules_of("""
        import os

        def a(d):
            path = os.path.join(d, "checkpoint.zip")
            return path

        def b(d, data):
            path = os.path.join(d, "stats.bin")
            with open(path, "wb") as f:
                f.write(data)
    """)


# ---------------------------------------------------------------- JX015
def test_jx015_positive_astype_on_device_value_in_loop():
    assert "JX015" in rules_of("""
        import jax.numpy as jnp

        def train(step, batches, params):
            xb = jnp.zeros((4, 4))
            for b in batches:
                xb = xb.astype(jnp.bfloat16)    # cast dispatch per step
                params = step(params, xb)
            return params
    """)


def test_jx015_positive_dtype_ctor_in_loop():
    assert "JX015" in rules_of("""
        import jax.numpy as jnp

        def train(step, params, lr):
            for i in range(100):
                params = step(params, jnp.float32(lr))
            return params
    """)


def test_jx015_negative_host_numpy_hoisted_and_jit():
    assert "JX015" not in rules_of("""
        import jax
        import jax.numpy as jnp
        import numpy as np

        def etl(batches):
            out = []
            for b in batches:
                out.append(b.astype(np.float32))   # host ETL: legal
            return out

        def train(step, params, batches, lr):
            lr_s = jnp.float32(lr)                 # hoisted: placed once
            for b in batches:
                params = step(params, b, lr_s, np.float32(0.1))
            return params

        @jax.jit
        def f(x):
            for i in range(3):
                x = x.astype(jnp.bfloat16)         # traced, not dispatched
            return x
    """)


def test_jx015_pragma_suppresses():
    assert "JX015" not in rules_of("""
        import jax.numpy as jnp

        def probe(step, params):
            for i in range(3):
                step(params, jnp.float32(i))  # graftlint: disable=JX015  (3-iteration probe)
    """)


# ---------------------------------------------------------------- JX016
def test_jx016_positive_unbounded_reconnect_loop():
    assert "JX016" in rules_of("""
        import socket

        def keep_publishing(host, port, frames):
            while True:
                try:
                    sock = socket.create_connection((host, port))
                    for f in frames:
                        sock.sendall(f)
                    return
                except OSError:
                    continue          # hammers a dead hub forever
    """)


def test_jx016_positive_retry_reaches_nested_try():
    assert "JX016" in rules_of("""
        def poll_forever(fetch):
            while True:
                try:
                    return fetch()
                except ConnectionError:
                    fetch = fetch
                    continue
    """)


def test_jx016_negative_backoff_and_budget():
    assert "JX016" not in rules_of("""
        import time

        def with_backoff(connect, policy):
            while True:
                try:
                    return connect()
                except OSError:
                    policy.sleep(1)       # budgeted backoff: legal
                    continue

        def with_budget(connect):
            attempt = 0
            while True:
                try:
                    return connect()
                except OSError:
                    attempt += 1
                    if attempt > 3:
                        raise
                    continue

        def bounded_for(connect, policy):
            for attempt in range(3):      # bounded loop, not while True
                try:
                    return connect()
                except OSError:
                    continue
    """)


def test_jx016_negative_queue_drain_and_inner_loop():
    assert "JX016" not in rules_of("""
        import queue

        def drain(q):
            while True:
                try:
                    item = q.get_nowait()   # break, not continue: a drain
                except queue.Empty:
                    break
                yield item

        def outer(jobs, run):
            while True:
                for j in jobs:
                    try:
                        run(j)
                    except RuntimeError:
                        continue         # binds to the inner for loop
                return
    """)


def test_jx016_pragma_suppresses():
    assert "JX016" not in rules_of("""
        def spin(connect):
            while True:
                try:
                    return connect()
                except OSError:  # graftlint: disable=JX016  (probe rig)
                    continue
    """)


# ---------------------------------------------------------------- JX017
_SERVING_PATH = "deeplearning4j_tpu/serving/fix.py"


def rules_at(src: str, path: str):
    return {f.rule for f in lint_source(textwrap.dedent(src), path)}


def test_jx017_positive_unbounded_queues_in_serving_scope():
    src = """
        import queue
        import multiprocessing as mp
        from queue import Queue

        def build():
            a = queue.Queue()          # unbounded
            b = mp.Queue()             # unbounded
            c = Queue()                # unbounded (from-import)
            return a, b, c
    """
    findings = lint_source(textwrap.dedent(src), _SERVING_PATH)
    assert sum(f.rule == "JX017" for f in findings) == 3


def test_jx017_positive_streaming_and_parallel_scope():
    src = """
        import queue

        q = queue.PriorityQueue()
    """
    for path in ("deeplearning4j_tpu/streaming/fix.py",
                 "deeplearning4j_tpu/parallel/fix.py"):
        assert "JX017" in rules_at(src, path)


def test_jx017_negative_bounded_or_deliberate():
    assert "JX017" not in rules_at("""
        import queue
        import multiprocessing as mp

        def build(limit):
            a = queue.Queue(maxsize=limit)    # keyword bound
            b = queue.Queue(256)              # positional bound
            c = mp.Queue(maxsize=0)           # deliberate unboundedness
            return a, b, c
    """, _SERVING_PATH)


def test_jx017_negative_out_of_scope_module():
    # ETL/data modules size queues to their prefetch depth — out of scope
    assert "JX017" not in rules_at("""
        import queue

        q = queue.Queue()
    """, "deeplearning4j_tpu/data/fix.py")
    assert "JX017" not in rules_of("""
        import queue

        q = queue.Queue()
    """)


def test_jx017_pragma_suppresses():
    assert "JX017" not in rules_at("""
        import queue

        q = queue.Queue()  # graftlint: disable=JX017  (drained every tick)
    """, _SERVING_PATH)


# ------------------------------------------------------------- pragmas
def test_pragma_same_line_suppresses():
    assert "JX007" not in rules_of("""
        def f():
            try:
                return 1
            except:  # graftlint: disable=JX007
                return 2
    """)


def test_pragma_standalone_line_suppresses_next_line():
    assert "JX008" not in rules_of("""
        # graftlint: disable=JX008
        def f(a, xs=[]):
            return a
    """)


def test_pragma_disable_file():
    src = """
        # graftlint: disable-file=JX007
        def f():
            try:
                return 1
            except:
                return 2

        def g():
            try:
                return 3
            except:
                return 4
    """
    assert "JX007" not in rules_of(src)


def test_pragma_only_suppresses_named_rule():
    got = rules_of("""
        def f(a, xs=[]):
            try:
                return a
            except:  # graftlint: disable=JX008
                return xs
    """)
    assert "JX007" in got        # pragma names the WRONG rule
    assert "JX008" in got        # JX008 is on the def line, not here


# ------------------------------------------------------------- baseline
def test_baseline_absorbs_exact_budget(tmp_path):
    src = textwrap.dedent("""
        def f():
            try:
                return 1
            except:
                return 2
    """)
    f = tmp_path / "m.py"
    f.write_text(src)
    found = lint_paths([str(f)])
    assert [x.rule for x in found] == ["JX007"]
    bl = Baseline.from_findings(found)
    assert bl.filter(found) == []
    # a SECOND bare except exceeds the budget
    f.write_text(src + textwrap.dedent("""
        def g():
            try:
                return 3
            except:
                return 4
    """))
    found2 = lint_paths([str(f)])
    assert len(found2) == 2
    assert len(bl.filter(found2)) == 1


def test_baseline_round_trips_through_json(tmp_path):
    bl = Baseline({"pkg/m.py::JX003": 2})
    p = tmp_path / "baseline.json"
    bl.save(str(p))
    loaded = Baseline.load(str(p))
    assert loaded.allowances == {"pkg/m.py::JX003": 2}
    assert Baseline.load(str(tmp_path / "missing.json")).allowances == {}


# ------------------------------------------------------------------ CLI
def test_cli_text_and_json_and_exit_codes(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("def f(a, xs=[]):\n    return a\n")
    env_root = str(REPO_ROOT)
    r = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "--no-baseline", str(bad)],
        capture_output=True, text=True, cwd=env_root)
    assert r.returncode == 1
    assert "JX008" in r.stdout
    r = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "--no-baseline",
         "--format", "json", str(bad)],
        capture_output=True, text=True, cwd=env_root)
    data = json.loads(r.stdout)
    assert data and data[0]["rule"] == "JX008"
    good = tmp_path / "good.py"
    good.write_text("def f(a, xs=None):\n    return a\n")
    r = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "--no-baseline", str(good)],
        capture_output=True, text=True, cwd=env_root)
    assert r.returncode == 0
    assert "clean" in r.stdout


def test_syntax_error_reported_not_crashed():
    got = lint_source("def f(:\n", "broken.py")
    assert [f.rule for f in got] == ["JX000"]


# ------------------------------------------------------------- the gate
def test_every_rule_has_docs():
    assert set(RULES) == set(RULE_DOCS)
    assert len(RULES) == 17


def test_package_is_clean_modulo_baseline():
    """THE tier-1 gate: every future PR re-lints the whole package."""
    found = lint_paths([str(PKG)])
    kept = Baseline.load(str(BASELINE)).filter(found)
    assert kept == [], "\n".join(f.format() for f in kept)


def test_baseline_is_near_empty():
    """The checked-in baseline must stay justified-in-review small."""
    bl = Baseline.load(str(BASELINE))
    assert sum(bl.allowances.values()) <= 5, bl.allowances


def test_no_bare_except_in_package():
    """ISSUE 1 acceptance: zero bare `except:` clauses in the package."""
    found = [f for f in lint_paths([str(PKG)], select=["JX007"])]
    assert found == [], "\n".join(f.format() for f in found)


# ----------------------------------------------- review-hardening fixes
def test_pragma_allows_trailing_justification():
    """The documented pragma form carries a justifying comment after the
    code list; it must still suppress."""
    assert "JX007" not in rules_of("""
        def f():
            try:
                return 1
            except:  # graftlint: disable=JX007   (cleanup must never raise)
                return 2
    """)
    assert "JX008" not in rules_of("""
        def f(a, xs=[], m={}):  # graftlint: disable=JX008, JX007 shared cache
            return a
    """)


def test_nonexistent_path_errors_instead_of_clean(tmp_path):
    with pytest.raises(FileNotFoundError):
        lint_paths([str(tmp_path / "no_such_dir")])


def test_non_py_file_argument_errors(tmp_path):
    f = tmp_path / "notes.txt"
    f.write_text("hello")
    with pytest.raises(ValueError, match="not a .py file"):
        lint_paths([str(f)])


def test_unknown_select_code_errors():
    with pytest.raises(ValueError, match="unknown rule code"):
        lint_source("x = 1\n", "m.py", select=["JXBOGUS"])
    with pytest.raises(ValueError, match="unknown rule code"):
        lint_source("x = 1\n", "m.py", ignore=["JX03"])


def test_cli_typo_path_exits_nonzero(tmp_path):
    r = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", str(tmp_path / "typo_dir")],
        capture_output=True, text=True, cwd=str(REPO_ROOT))
    assert r.returncode == 2
    assert "no such file" in r.stderr


def test_ui_numeric_style_fields_escaped_on_wire():
    """Declared-numeric style fields are NOT type-checked by the serde,
    so a string riding in where an int is expected must still escape."""
    from deeplearning4j_tpu.ui import (ComponentDiv, StyleDiv,
                                       component_from_json,
                                       component_to_json)
    payload = '"><script>alert(1)</script>'
    d = ComponentDiv(style=StyleDiv(width=100, float_value=payload))
    wire = component_to_json(d)
    out = component_from_json(wire).render()
    assert "<script>" not in out
    assert "&quot;&gt;&lt;script&gt;" in out
    # string smuggled into a declared-int field over the wire
    wire2 = wire.replace("100", json.dumps(payload).strip('"') and
                         json.dumps(payload))
    out2 = component_from_json(wire2).render()
    assert "<script>" not in out2
