"""graftlint: per-rule positive/negative fixtures + the tier-1 gate that
keeps ``deeplearning4j_tpu/`` clean modulo the checked-in baseline.

Every rule JX001–JX031 has at least one fixture that MUST fire and one
that MUST stay silent; the whole-program concurrency pass (JX018–JX021)
additionally unit-tests its thread-entry / guarded-by / lock-order
inference layers.  The gate test makes every future PR re-lint the whole
package without separate CI wiring, and the wall-time budget test keeps
the full run inside the developer loop.
"""
import json
import os
import subprocess
import sys
import textwrap
import time
from pathlib import Path

import pytest

REPO_ROOT = Path(__file__).resolve().parents[1]
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))

from tools.graftlint import (Baseline, PROGRAM_RULES,  # noqa: E402
                             RULE_DOCS, RULES, lint_paths, lint_source)

PKG = REPO_ROOT / "deeplearning4j_tpu"
BASELINE = REPO_ROOT / "tools" / "graftlint" / "baseline.json"


def rules_of(src: str):
    return {f.rule for f in lint_source(textwrap.dedent(src), "fix.py")}


def findings(src: str, select=None):
    return lint_source(textwrap.dedent(src), "fix.py", select=select)


# ---------------------------------------------------------------- JX001
def test_jx001_positive_numpy_on_traced_value():
    assert "JX001" in rules_of("""
        import jax
        import numpy as np

        @jax.jit
        def f(x):
            return np.log(x)
    """)


def test_jx001_positive_jit_call_form():
    assert "JX001" in rules_of("""
        import jax
        import numpy as np

        def f(x):
            return np.tanh(x * 2)

        g = jax.jit(f)
    """)


def test_jx001_negative_host_constant_and_unjitted():
    assert "JX001" not in rules_of("""
        import jax
        import jax.numpy as jnp
        import numpy as np

        @jax.jit
        def f(x):
            scale = np.log(2.0)      # host constant: runs once at trace
            return jnp.log(x) * scale

        def g(x):
            return np.log(x)         # not a jit scope
    """)


# ---------------------------------------------------------------- JX002
def test_jx002_positive_if_on_tracer():
    assert "JX002" in rules_of("""
        import jax

        @jax.jit
        def f(x):
            if x > 0:
                return x
            return -x
    """)


def test_jx002_positive_while_on_derived_value():
    assert "JX002" in rules_of("""
        import jax

        @jax.jit
        def f(x):
            y = x * 2
            while y < 10:
                y = y + 1
            return y
    """)


def test_jx002_negative_static_arg_and_shape():
    assert "JX002" not in rules_of("""
        import functools
        import jax

        @functools.partial(jax.jit, static_argnames=("mode",))
        def f(x, mode):
            if mode == "fast":          # static arg: concrete at trace
                return x
            if x.shape[0] > 1:          # shape is trace-static
                return x + 1
            if len(x) > 2:              # len() is static too
                return x + 2
            return x
    """)


def test_jx002_negative_static_argnums_positional():
    assert "JX002" not in rules_of("""
        import jax

        def f(x, k):
            if k > 2:
                return x * k
            return x

        g = jax.jit(f, static_argnums=(1,))
    """)


# ---------------------------------------------------------------- JX003
def test_jx003_positive_float_and_item_in_fit_loop():
    got = findings("""
        import jax

        def fit(model, batches, step):
            for b in batches:
                loss = step(b)
                model.score = float(loss)
                model.last = loss.item()
    """, select=["JX003"])
    assert len(got) == 2


def test_jx003_negative_shape_reads_and_after_loop():
    assert "JX003" not in rules_of("""
        import jax
        import numpy as np

        def fit(model, batches, step):
            loss = None
            for b in batches:
                n = int(b.shape[0])            # static metadata
                m = int(getattr(b, "shape", (0,))[0])
                idx = np.array([i for i in range(n)])  # host ETL
                loss = step(b)
            model.score = float(loss)          # one sync after the loop
    """)


def test_jx003_negative_not_a_training_function():
    assert "JX003" not in rules_of("""
        import jax

        def report(values):
            out = []
            for v in values:
                out.append(float(v))
            return out
    """)


def test_jx003_negative_module_without_jax():
    assert "JX003" not in rules_of("""
        def fit(model, batches):
            for b in batches:
                model.score = float(b)
    """)


# ---------------------------------------------------------------- JX004
def test_jx004_positive_jit_in_loop():
    assert "JX004" in rules_of("""
        import jax

        def run(fs, x):
            outs = []
            for f in fs:
                outs.append(jax.jit(f)(x))
            return outs
    """)


def test_jx004_positive_immediate_invocation():
    assert "JX004" in rules_of("""
        import jax

        def once(f, x):
            return jax.jit(f)(x)
    """)


def test_jx004_negative_hoisted_jit():
    assert "JX004" not in rules_of("""
        import jax

        def make_step(f):
            step = jax.jit(f)
            def run(xs):
                return [step(x) for x in xs]
            return run
    """)


# ---------------------------------------------------------------- JX005
def test_jx005_positive_list_static_argnums():
    assert "JX005" in rules_of("""
        import jax

        def f(x, k):
            return x * k

        g = jax.jit(f, static_argnums=[1])
    """)


def test_jx005_negative_tuple_static_argnums():
    assert "JX005" not in rules_of("""
        import jax

        def f(x, k):
            return x * k

        g = jax.jit(f, static_argnums=(1,))
        h = jax.jit(f, static_argnames=("k",))
    """)


# ---------------------------------------------------------------- JX006
def test_jx006_positive_self_mutation():
    assert "JX006" in rules_of("""
        import jax

        class Model:
            @jax.jit
            def step(self, x):
                self.calls = self.calls + 1
                return x * 2
    """)


def test_jx006_positive_global_mutation():
    assert "JX006" in rules_of("""
        import jax

        COUNT = 0

        @jax.jit
        def f(x):
            global COUNT
            COUNT += 1
            return x
    """)


def test_jx006_negative_local_state_and_unjitted():
    assert "JX006" not in rules_of("""
        import jax

        class Model:
            @jax.jit
            def step(self, x):
                y = x * 2          # locals are fine
                return y

            def host_update(self):
                self.calls = 1     # not traced: fine
    """)


# ---------------------------------------------------------------- JX007
def test_jx007_positive_bare_except():
    assert "JX007" in rules_of("""
        def f():
            try:
                return 1
            except:
                return 2
    """)


def test_jx007_negative_typed_except():
    assert "JX007" not in rules_of("""
        def f():
            try:
                return 1
            except Exception:
                return 2
            except (ValueError, OSError):
                return 3
    """)


# ---------------------------------------------------------------- JX008
def test_jx008_positive_mutable_defaults():
    got = findings("""
        def f(a, xs=[], m={}):
            return a

        def g(b, s=set()):
            return b
    """, select=["JX008"])
    assert len(got) == 3


def test_jx008_negative_none_and_immutable_defaults():
    assert "JX008" not in rules_of("""
        def f(a, xs=None, t=(), name="x", n=3):
            xs = [] if xs is None else xs
            return a
    """)


# ---------------------------------------------------------------- JX009
def test_jx009_positive_unsynced_timing():
    assert "JX009" in rules_of("""
        import time
        import jax.numpy as jnp

        def bench(f, x):
            t0 = time.perf_counter()
            y = f(x) + jnp.ones(3)
            return time.perf_counter() - t0
    """)


def test_jx009_negative_block_until_ready():
    assert "JX009" not in rules_of("""
        import time
        import jax
        import jax.numpy as jnp

        def bench(f, x):
            t0 = time.perf_counter()
            y = f(x) + jnp.ones(3)
            jax.block_until_ready(y)
            return time.perf_counter() - t0
    """)


def test_jx009_negative_fetch_closed_and_deadlines():
    assert "JX009" not in rules_of("""
        import time
        import numpy as np
        import jax.numpy as jnp

        def bench(f, x):
            t0 = time.perf_counter()
            y = float(np.asarray(f(x))[0])   # fetch closes the async gap
            return time.perf_counter() - t0

        def poll(q, timeout):
            deadline = time.time() + timeout   # deadline, not measurement
            while time.time() < deadline:
                v = q.get()
                if v is not None:
                    return v * jnp.ones(1)
    """)


# ---------------------------------------------------------------- JX010
def test_jx010_positive_float64_astype():
    assert "JX010" in rules_of("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            return x.astype(jnp.float64)
    """)


def test_jx010_positive_dtype_string():
    assert "JX010" in rules_of("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            return jnp.zeros_like(x, dtype="float64")
    """)


def test_jx010_negative_float32_and_outside_jit():
    assert "JX010" not in rules_of("""
        import jax
        import jax.numpy as jnp
        import numpy as np

        @jax.jit
        def f(x):
            return x.astype(jnp.float32)

        def host(x):
            return np.float64(x)   # host-side double is fine
    """)


# ---------------------------------------------------------------- JX011
def test_jx011_positive_interval_subtraction():
    assert "JX011" in rules_of("""
        import time

        def measure(f):
            t0 = time.time()
            f()
            return time.time() - t0
    """)


def test_jx011_positive_propagated_sample_and_bare_import():
    # one-hop propagation (now -> self._last) across methods, with
    # `from time import time`
    assert "JX011" in rules_of("""
        from time import time

        class Listener:
            def start(self):
                now = time()
                self._last = now

            def rate(self, n):
                now = time()
                return n / (now - self._last)
    """)


def test_jx011_negative_deadline_idiom_and_timestamps():
    assert "JX011" not in rules_of("""
        import time

        def wait(poll, timeout):
            deadline = time.time() + timeout
            while time.time() < deadline:
                remaining = deadline - time.time()  # remaining, not elapsed
                poll(remaining)

        def stamp(record):
            record["ts"] = time.time()   # timestamp: no arithmetic
            return record
    """)


def test_jx011_negative_perf_counter_interval():
    assert "JX011" not in rules_of("""
        import time

        def measure(f):
            t0 = time.perf_counter()
            f()
            return time.perf_counter() - t0
    """)


# ---------------------------------------------------------------- JX012
def test_jx012_positive_device_put_in_loop():
    assert "JX012" in rules_of("""
        import jax

        def feed(step, batches):
            for b in batches:
                step(jax.device_put(b))
    """)


def test_jx012_positive_bare_device_put_in_while():
    assert "JX012" in rules_of("""
        from jax import device_put

        def feed(step, batches):
            while batches:
                step(device_put(batches.pop()))
    """)


def test_jx012_positive_asarray_of_device_value_in_loop():
    assert "JX012" in rules_of("""
        import jax.numpy as jnp
        import numpy as np

        def collect(xs):
            d = jnp.asarray(xs)
            out = []
            for i in range(10):
                out.append(np.asarray(d))   # D2H fetch every iteration
            return out
    """)


def test_jx012_negative_hoisted_and_host_and_jit():
    assert "JX012" not in rules_of("""
        import jax
        import jax.numpy as jnp
        import numpy as np

        def place(b):
            return jax.device_put(b)        # no loop: a prefetch stage

        def loop(items):
            total = 0.0
            for it in items:
                a = np.asarray(it)          # host list -> host array
                total += a.sum()
            return total

        @jax.jit
        def f(x):
            for i in range(3):              # unrolled at trace time
                x = jax.device_put(x)
            return x
    """)


def test_jx012_pragma_suppresses():
    assert "JX012" not in rules_of("""
        import jax

        def prefetch(batches):
            for b in batches:
                yield jax.device_put(b)  # graftlint: disable=JX012  (the prefetch stage itself)
    """)


# ---------------------------------------------------------------- JX013
def test_jx013_positive_method_local_jit_closes_over_self():
    assert "JX013" in rules_of("""
        import jax

        class Net:
            def make_step(self):
                def step(params, x):
                    return params * self.scale + x
                return jax.jit(step)
    """)


def test_jx013_positive_decorated_def_inside_method():
    assert "JX013" in rules_of("""
        import jax

        class Net:
            def fit(self, x):
                @jax.jit
                def step(p):
                    return self.forward(p, x)
                return step(self.params)
    """)


def test_jx013_positive_lambda_argument():
    assert "JX013" in rules_of("""
        import jax

        class Net:
            def make(self):
                return jax.jit(lambda x: x * self.scale)
    """)


def test_jx013_negative_self_free_closure_and_module_level():
    assert "JX013" not in rules_of("""
        import jax

        def build_step(conf, tx):
            def step(params, x):
                return params * conf.scale + tx(x)
            return jax.jit(step)

        class Net:
            def make_step(self):
                conf = self.conf
                def step(params, x):       # closes over conf, NOT self
                    return params * conf.scale + x
                return jax.jit(step)
    """)


def test_jx013_negative_jit_outside_methods():
    assert "JX013" not in rules_of("""
        import jax

        def helper(f):
            def step(x):
                return f(x)
            return jax.jit(step)
    """)


# ---------------------------------------------------------------- JX014
def test_jx014_positive_zipfile_write_to_checkpoint_path():
    assert "JX014" in rules_of("""
        import os
        import zipfile

        def save(d, tag, payload):
            path = os.path.join(d, f"checkpoint_{tag}.zip")
            with zipfile.ZipFile(path, "w") as zf:
                zf.writestr("a", payload)
    """)


def test_jx014_positive_open_wb_on_ckpt_name_and_savez_model_zip():
    got = rules_of("""
        import numpy as np

        def save(d, data, arrs, ckpt_file):
            with open(ckpt_file, "wb") as f:
                f.write(data)
            np.savez(d + "/bestModel.zip", **arrs)
    """)
    assert "JX014" in got


def test_jx014_positive_one_hop_alias():
    assert "JX014" in rules_of("""
        import os

        def save(d, data):
            path = os.path.join(d, "ckpt-00000001.bin")
            dst = path
            with open(dst, "wb") as f:
                f.write(data)
    """)


def test_jx014_negative_atomic_helper_reads_and_plain_paths():
    assert "JX014" not in rules_of("""
        import io
        import zipfile
        import numpy as np
        from deeplearning4j_tpu.faulttolerance.atomic import atomic_file

        def save(dst, arrs, log_path, ckpt_path, shard_path, data):
            with atomic_file(dst) as tmp:          # helper: tmp is runtime
                with zipfile.ZipFile(tmp, "w") as zf:
                    zf.writestr("a", b"x")
            buf = io.BytesIO()
            np.savez(buf, **arrs)                  # in-memory buffer
            with open(log_path, "wb") as f:        # not checkpoint-like
                f.write(data)
            with zipfile.ZipFile(ckpt_path, "r") as zf:    # read-only
                zf.namelist()
            np.savez(shard_path, **arrs)           # not checkpoint-like
            with open(ckpt_path, "w") as f:        # text mode: manifest
                f.write("{}")                      # writers go via helper,
                                                   # but rule targets "wb"
    """)


def test_jx014_negative_same_name_in_unrelated_function():
    # name taint is per-scope: `path` holding a checkpoint name in one
    # function must not flag an unrelated `path` written elsewhere
    assert "JX014" not in rules_of("""
        import os

        def a(d):
            path = os.path.join(d, "checkpoint.zip")
            return path

        def b(d, data):
            path = os.path.join(d, "stats.bin")
            with open(path, "wb") as f:
                f.write(data)
    """)


# ---------------------------------------------------------------- JX015
def test_jx015_positive_astype_on_device_value_in_loop():
    assert "JX015" in rules_of("""
        import jax.numpy as jnp

        def train(step, batches, params):
            xb = jnp.zeros((4, 4))
            for b in batches:
                xb = xb.astype(jnp.bfloat16)    # cast dispatch per step
                params = step(params, xb)
            return params
    """)


def test_jx015_positive_dtype_ctor_in_loop():
    assert "JX015" in rules_of("""
        import jax.numpy as jnp

        def train(step, params, lr):
            for i in range(100):
                params = step(params, jnp.float32(lr))
            return params
    """)


def test_jx015_negative_host_numpy_hoisted_and_jit():
    assert "JX015" not in rules_of("""
        import jax
        import jax.numpy as jnp
        import numpy as np

        def etl(batches):
            out = []
            for b in batches:
                out.append(b.astype(np.float32))   # host ETL: legal
            return out

        def train(step, params, batches, lr):
            lr_s = jnp.float32(lr)                 # hoisted: placed once
            for b in batches:
                params = step(params, b, lr_s, np.float32(0.1))
            return params

        @jax.jit
        def f(x):
            for i in range(3):
                x = x.astype(jnp.bfloat16)         # traced, not dispatched
            return x
    """)


def test_jx015_pragma_suppresses():
    assert "JX015" not in rules_of("""
        import jax.numpy as jnp

        def probe(step, params):
            for i in range(3):
                step(params, jnp.float32(i))  # graftlint: disable=JX015  (3-iteration probe)
    """)


# ---------------------------------------------------------------- JX016
def test_jx016_positive_unbounded_reconnect_loop():
    assert "JX016" in rules_of("""
        import socket

        def keep_publishing(host, port, frames):
            while True:
                try:
                    sock = socket.create_connection((host, port))
                    for f in frames:
                        sock.sendall(f)
                    return
                except OSError:
                    continue          # hammers a dead hub forever
    """)


def test_jx016_positive_retry_reaches_nested_try():
    assert "JX016" in rules_of("""
        def poll_forever(fetch):
            while True:
                try:
                    return fetch()
                except ConnectionError:
                    fetch = fetch
                    continue
    """)


def test_jx016_negative_backoff_and_budget():
    assert "JX016" not in rules_of("""
        import time

        def with_backoff(connect, policy):
            while True:
                try:
                    return connect()
                except OSError:
                    policy.sleep(1)       # budgeted backoff: legal
                    continue

        def with_budget(connect):
            attempt = 0
            while True:
                try:
                    return connect()
                except OSError:
                    attempt += 1
                    if attempt > 3:
                        raise
                    continue

        def bounded_for(connect, policy):
            for attempt in range(3):      # bounded loop, not while True
                try:
                    return connect()
                except OSError:
                    continue
    """)


def test_jx016_negative_queue_drain_and_inner_loop():
    assert "JX016" not in rules_of("""
        import queue

        def drain(q):
            while True:
                try:
                    item = q.get_nowait()   # break, not continue: a drain
                except queue.Empty:
                    break
                yield item

        def outer(jobs, run):
            while True:
                for j in jobs:
                    try:
                        run(j)
                    except RuntimeError:
                        continue         # binds to the inner for loop
                return
    """)


def test_jx016_pragma_suppresses():
    assert "JX016" not in rules_of("""
        def spin(connect):
            while True:
                try:
                    return connect()
                except OSError:  # graftlint: disable=JX016  (probe rig)
                    continue
    """)


# ---------------------------------------------------------------- JX017
_SERVING_PATH = "deeplearning4j_tpu/serving/fix.py"


def rules_at(src: str, path: str):
    return {f.rule for f in lint_source(textwrap.dedent(src), path)}


def test_jx017_positive_unbounded_queues_in_serving_scope():
    src = """
        import queue
        import multiprocessing as mp
        from queue import Queue

        def build():
            a = queue.Queue()          # unbounded
            b = mp.Queue()             # unbounded
            c = Queue()                # unbounded (from-import)
            return a, b, c
    """
    findings = lint_source(textwrap.dedent(src), _SERVING_PATH)
    assert sum(f.rule == "JX017" for f in findings) == 3


def test_jx017_positive_streaming_and_parallel_scope():
    src = """
        import queue

        q = queue.PriorityQueue()
    """
    for path in ("deeplearning4j_tpu/streaming/fix.py",
                 "deeplearning4j_tpu/parallel/fix.py"):
        assert "JX017" in rules_at(src, path)


def test_jx017_negative_bounded_or_deliberate():
    assert "JX017" not in rules_at("""
        import queue
        import multiprocessing as mp

        def build(limit):
            a = queue.Queue(maxsize=limit)    # keyword bound
            b = queue.Queue(256)              # positional bound
            c = mp.Queue(maxsize=0)           # deliberate unboundedness
            return a, b, c
    """, _SERVING_PATH)


def test_jx017_negative_out_of_scope_module():
    # ETL/data modules size queues to their prefetch depth — out of scope
    assert "JX017" not in rules_at("""
        import queue

        q = queue.Queue()
    """, "deeplearning4j_tpu/data/fix.py")
    assert "JX017" not in rules_of("""
        import queue

        q = queue.Queue()
    """)


def test_jx017_pragma_suppresses():
    assert "JX017" not in rules_at("""
        import queue

        q = queue.Queue()  # graftlint: disable=JX017  (drained every tick)
    """, _SERVING_PATH)


# ---------------------------------------------------------------- JX022
def test_jx022_positive_registry_lookup_in_loop():
    src = """
        def consume(messages, reg):
            for m in messages:
                reg.counter("broker_messages_total", "doc").inc()

        def poll(reg):
            while True:
                reg.gauge("queue_depth", "doc").set(1)
    """
    fs = findings(src)
    assert sum(f.rule == "JX022" for f in fs) == 2


def test_jx022_positive_constant_labels_in_loop():
    assert "JX022" in rules_of("""
        def run(batches, etl_h):
            for b in batches:
                etl_h.labels("fetch").observe(0.1)
    """)


def test_jx022_negative_cached_child_and_varying_labels():
    assert "JX022" not in rules_of("""
        def run(batches, reg):
            c = reg.counter("training_steps_total", "doc")
            age = reg.gauge("hb_age", "doc", ("worker",))
            for i, b in enumerate(batches):
                c.inc()
                age.labels(str(i)).set(1.0)   # varying label: legal
    """)


def test_jx022_negative_lookup_outside_loop_and_non_registry():
    assert "JX022" not in rules_of("""
        import collections

        def setup(reg):
            return reg.histogram("x_seconds", "doc")

        def tally(items):
            for it in items:
                c = collections.Counter(it)     # not a registry lookup
            return c
    """)


def test_jx022_pragma_suppresses():
    assert "JX022" not in rules_of("""
        def run(batches, reg):
            for b in batches:
                reg.counter("x_total", "d").inc()  # graftlint: disable=JX022  (cold loop)
    """)


# ---------------------------------------------------------------- JX023
_GENERATION_PATH = "deeplearning4j_tpu/generation/fix.py"


def test_jx023_positive_per_token_syncs_in_decode_scope():
    src = """
        import numpy as np

        def decode_tokens(model, tok, n):
            out = []
            for _ in range(n):
                logits = model.decode(tok)
                tok = float(logits)              # per-token host sync
                out.append(tok)
            return out

        def drain(engine):
            while engine.alive():
                dev = engine.poll()
                host = np.asarray(dev)           # per-token host sync
                yield host

        def emit(rows):
            for r in rows:
                yield r.item()                   # per-token host sync
    """
    for path in (_GENERATION_PATH, _SERVING_PATH):
        fs = lint_source(textwrap.dedent(src), path)
        assert sum(f.rule == "JX023" for f in fs) == 3, path


def test_jx023_negative_out_of_scope_path():
    # the identical per-token sync outside generation//serving/ is JX003
    # territory (training loops) or legal ETL — JX023 stays silent
    assert "JX023" not in rules_at("""
        import numpy as np

        def decode_tokens(model, tok, n):
            out = []
            for _ in range(n):
                tok = float(model.decode(tok))
                out.append(tok)
            return out
    """, "deeplearning4j_tpu/data/fix.py")


def test_jx023_negative_batched_materialization_at_step_boundary():
    # the engine contract: ONE np.asarray per decode step for the whole
    # slot batch, host-side int() on the already-materialized array rows
    assert "JX023" not in rules_at("""
        import numpy as np

        def decode_step(model, toks, caches, occupants):
            out_dev, caches = model.decode(toks, caches)
            out = np.asarray(out_dev)            # once per STEP: legal
            for slot, req in occupants.items():
                req.emit(int(out[slot]))         # host array row, no sync
            return caches
    """, _GENERATION_PATH)


def test_jx023_negative_host_only_module_and_list_etl():
    # pure-host modules (no jax/numpy import) have nothing to sync on,
    # and np.asarray FROM a list literal is host ETL, not a device fetch
    assert "JX023" not in rules_at("""
        def drain(q):
            while True:
                ev = q.get()
                yield ev.item()
    """, _GENERATION_PATH)
    assert "JX023" not in rules_at("""
        import numpy as np

        def pack(rows):
            for r in rows:
                yield np.asarray([1, 2, 3])
    """, _GENERATION_PATH)


def test_jx023_pragma_suppresses():
    assert "JX023" not in rules_at("""
        import numpy as np

        def warmup(model, buckets):
            for b in buckets:
                np.asarray(model.forward(b))  # graftlint: disable=JX023  (warmup: block per compile)
    """, _SERVING_PATH)


# ---------------------------------------------------------------- JX024
_PARALLEL_PATH = "deeplearning4j_tpu/parallel/fix.py"
_NN_PATH = "deeplearning4j_tpu/nn/fix.py"


def test_jx024_positive_full_pytree_materialization_in_step_loop():
    src = """
        import jax
        import jax.numpy as jnp
        import numpy as np

        def fit(step, params, opt_state, batches):
            for x, y in batches:
                params, opt_state = step(params, opt_state, x, y)
                host = np.asarray(params)        # full-model host copy
            return params

        def monitor(step, params, batches):
            for b in batches:
                params = step(params, b)
                snap = jax.device_get(params)    # full-model host copy
                print(snap)

        def gathered_update(params, grads, steps):
            i = 0
            while i < steps:
                full = jax.lax.all_gather(params, "data")  # resident global params
                params = full - 0.1 * grads
                i += 1
            return params
    """
    for path in (_PARALLEL_PATH, _NN_PATH):
        fs = lint_source(textwrap.dedent(src), path)
        assert sum(f.rule == "JX024" for f in fs) == 3, path


def test_jx024_negative_out_of_scope_and_boundaries():
    # same spellings outside parallel//nn/ are other rules' territory
    assert "JX024" not in rules_at("""
        import numpy as np

        def fit(step, params, batches):
            for b in batches:
                params = step(params, b)
                np.asarray(params)
    """, "deeplearning4j_tpu/serving/fix.py")
    # checkpoint/serialize boundaries materialize OUTSIDE the loop, and
    # per-batch materialization of non-params values stays legal
    assert "JX024" not in rules_at("""
        import jax
        import numpy as np

        def fit(step, params, batches):
            for x, y in batches:
                params, loss = step(params, x, y)
                score = float(loss)
            return np.asarray(params)            # once, at the boundary

        def collect(step, params, batches):
            out = []
            for b in batches:
                params, logits = step(params, b)
                out.append(np.asarray(logits))   # activations, not params
            return out
    """, _PARALLEL_PATH)


def test_jx024_pragma_suppresses():
    assert "JX024" not in rules_at("""
        import jax

        def debug_fit(step, params, batches):
            for b in batches:
                params = step(params, b)
                jax.device_get(params)  # graftlint: disable=JX024  (debug digest per step)
    """, _PARALLEL_PATH)


# ---------------------------------------------------------------- JX025
_FT_PATH = "deeplearning4j_tpu/faulttolerance/fix.py"


def test_jx025_positive_unbudgeted_rendezvous_waits():
    src = """
        import time

        def wait_for_markers(stage, expected):
            while True:
                have = scan(stage)
                if not (expected - have):
                    break
                time.sleep(0.05)          # no deadline, no budget

        def lease_poll(store, want):
            missing = list(want)
            while missing:
                live = store.all_leases()
                missing = [w for w in want if w not in live]
                time.sleep(0.1)
    """
    for path in (_FT_PATH, "deeplearning4j_tpu/parallel/fix.py"):
        fs = lint_source(textwrap.dedent(src), path)
        assert sum(f.rule == "JX025" for f in fs) == 2, path


def test_jx025_negative_budgeted_and_cancellable_waits():
    # deadline-bounded, stop-event, drain-until-empty, attempt-budgeted
    # and out-of-scope waits all stay legal
    assert "JX025" not in rules_at("""
        import time

        def wait_for_markers(stage, expected, timeout_s):
            deadline = time.time() + timeout_s
            while True:
                if not (expected - scan(stage)):
                    break
                if time.time() > deadline:
                    raise TimeoutError("barrier timed out")
                time.sleep(0.05)

        def heartbeat(stop, interval):
            while not stop.wait(interval):
                renew()

        def beat_with_body_check(stop, broker):
            while True:
                broker.publish(b"hb")
                if stop.wait(0.5):
                    return

        def drain(sub):
            while True:
                payload = sub.poll(timeout=0.001)
                if payload is None:
                    break
                handle(payload)

        def retry(policy, worker):
            attempt = 0
            while attempt < policy.max_retries:
                attempt += 1
                policy.sleep(attempt, worker)
    """, _FT_PATH)
    # same spelling outside faulttolerance//parallel/ is out of scope
    assert "JX025" not in rules_at("""
        import time

        def wait(flag):
            while True:
                if flag():
                    break
                time.sleep(0.05)
    """, "deeplearning4j_tpu/serving/fix.py")


def test_jx025_pragma_suppresses():
    assert "JX025" not in rules_at("""
        import time

        def wait_forever(flag):
            while True:
                if flag():
                    break
                time.sleep(0.05)  # graftlint: disable=JX025  (test rig: the driver kills us)
    """, _FT_PATH)


# ---------------------------------------------------------------- JX026
_NN_PATH = "deeplearning4j_tpu/nn/fix.py"


def test_jx026_positive_debug_and_callbacks_in_package_module():
    src = """
        import jax
        from jax import pure_callback
        from jax.experimental import io_callback

        def step(params, x):
            jax.debug.print("x={x}", x=x)            # leftover debug
            jax.debug.breakpoint()                   # leftover debug
            y = pure_callback(host_fn, spec, x)      # host round-trip
            z = io_callback(logger, None, y)         # host round-trip
            return jax.pure_callback(host_fn, spec, z)
    """
    fs = lint_source(textwrap.dedent(src), _NN_PATH)
    assert sum(f.rule == "JX026" for f in fs) == 5


def test_jx026_positive_debug_module_aliases():
    # both spellings of a jax.debug module alias must fire: the
    # from-import and `import jax.debug as jdbg` (which binds the alias
    # name, so the dotted jax.debug.* branch never sees it)
    for imp, call in (("from jax import debug", "debug.print"),
                      ("import jax.debug as jdbg", "jdbg.print")):
        src = f"""
            {imp}

            def step(x):
                {call}("x={{x}}", x=x)
                return x
        """
        fs = lint_source(textwrap.dedent(src), _NN_PATH)
        assert sum(f.rule == "JX026" for f in fs) == 1, imp


def test_jx026_negative_test_modules_out_of_scope():
    # printing tracers is what debugging a test looks like — every
    # test-shaped path stays legal
    src = """
        import jax

        def test_step(x):
            jax.debug.print("x={x}", x=x)
            return x
    """
    for path in ("tests/test_step.py", "deeplearning4j_tpu/test_fix.py",
                 "tests/conftest.py"):
        assert "JX026" not in rules_at(src, path)


def test_jx026_negative_unrelated_names():
    # a user-defined pure_callback (no jax import of it) and non-debug
    # jax attrs don't fire
    assert "JX026" not in rules_at("""
        import jax

        def pure_callback(fn, spec, x):
            return fn(x)

        def step(x):
            y = pure_callback(abs, None, x)
            return jax.device_get(y)
    """, _NN_PATH)


def test_jx026_pragma_suppresses():
    src = """
        import jax

        def evaluate(x):
            jax.debug.print("eval={x}", x=x)  # graftlint: disable=JX026  (documented eval-only trace hook)
            return x
    """
    assert "JX026" not in {f.rule
                           for f in lint_source(textwrap.dedent(src),
                                                _NN_PATH)}


# ---------------------------------------------------------------- JX027
def test_jx027_positive_one_hot_matmul_lookup():
    src = """
        import jax
        import jax.numpy as jnp
        from jax.nn import one_hot

        def lookup(ids, W, vocab):
            a = jax.nn.one_hot(ids, vocab) @ W          # dense lookup
            b = one_hot(ids, vocab).T @ W               # transposed form
            c = W.T @ jax.nn.one_hot(ids, vocab)        # right operand
            return a + b.T + c.T
    """
    fs = lint_source(textwrap.dedent(src), _NN_PATH)
    assert sum(f.rule == "JX027" for f in fs) == 3


def test_jx027_positive_full_vocab_zeros_scatter():
    src = """
        import jax.numpy as jnp

        def dense_grad(rows, idx, n_in, dim):
            direct = jnp.zeros((n_in, dim)).at[idx].add(rows)
            buf = jnp.zeros((vocab_size, dim))
            hop = buf.at[idx].set(rows)                 # one-hop name
            return direct + hop
    """
    fs = lint_source(textwrap.dedent(src), _NN_PATH)
    assert sum(f.rule == "JX027" for f in fs) == 2


def test_jx027_positive_module_scope_and_jax_nn_import():
    # the two coverage gaps a review closed: `from jax import nn`
    # spells the same dense lookup, and a module/class-level scatter
    # is as dense as a function-local one
    src = """
        import jax.numpy as jnp
        from jax import nn

        DENSE = jnp.zeros((vocab_size, 16)).at[IDX].add(ROWS)

        class Table:
            cache = jnp.zeros((n_in, 8)).at[IDS].set(VALS)

        def lookup(ids, W, vocab):
            return nn.one_hot(ids, vocab) @ W
    """
    fs = lint_source(textwrap.dedent(src), _NN_PATH)
    assert sum(f.rule == "JX027" for f in fs) == 3


def test_jx027_negative_gather_and_small_buffers():
    # the gather path, a non-vocab zeros scatter, a one_hot without a
    # matmul, and a named one-hot matmul (kmeans' deliberate MXU
    # centroid sum) all stay legal
    assert "JX027" not in rules_at("""
        import jax
        import jax.numpy as jnp

        def ok(ids, W, points, bins, batch):
            z = W[ids]                                   # gather lookup
            hist = jnp.zeros((bins,)).at[ids].add(1.0)   # not vocab-sized
            oh = jax.nn.one_hot(ids, 4)                  # no matmul
            sums = oh.T @ points                         # named operand
            return z, hist, sums
    """, _NN_PATH)


def test_jx027_negative_test_modules_out_of_scope():
    src = """
        import jax
        import jax.numpy as jnp

        def test_dense_reference(ids, W, vocab):
            return jax.nn.one_hot(ids, vocab) @ W
    """
    for path in ("tests/test_embed.py", "tests/conftest.py"):
        assert "JX027" not in rules_at(src, path)


def test_jx027_pragma_suppresses():
    src = """
        import jax.numpy as jnp

        def to_dense(rows, idx, n_rows, dim):
            dense = jnp.zeros((n_rows, dim))
            return dense.at[idx].add(rows)  # graftlint: disable=JX027  (documented host-side interop densification)
    """
    assert "JX027" not in {f.rule
                           for f in lint_source(textwrap.dedent(src),
                                                _NN_PATH)}


# ---------------------------------------------------------------- JX028
def test_jx028_positive_every_stray_jit_spelling():
    # the four spellings the package sweep found: bare decorator,
    # functools.partial decorator, direct call, and the bare import
    src = """
        import functools
        import jax
        from jax import pmap

        @jax.jit
        def f(x):
            return x

        @functools.partial(jax.jit, static_argnames=("k",))
        def g(x, k):
            return x

        h = jax.jit(lambda x: x + 1)
    """
    fs = lint_source(textwrap.dedent(src), _NN_PATH)
    assert sum(f.rule == "JX028" for f in fs) == 4


def test_jx028_negative_compile_cache_and_tests_exempt():
    src = """
        import jax

        @jax.jit
        def f(x):
            return x
    """
    for path in ("deeplearning4j_tpu/nn/compile_cache.py",
                 "tests/test_fix.py", "tests/conftest.py"):
        assert "JX028" not in rules_at(src, path)


def test_jx028_negative_unrelated_jit_attributes():
    # a non-jax object's .jit attr and a user function named jit don't
    # fire; neither does routing through the sanctioned wrapper
    assert "JX028" not in rules_at("""
        from ..nn.compile_cache import InstrumentedJit

        def jit(fn):
            return fn

        def build(engine, step):
            prog = engine.jit(step)
            wrapped = jit(step)
            return InstrumentedJit(step, donate_argnums=(0,)), prog, wrapped
    """, _NN_PATH)


def test_jx028_pragma_suppresses():
    src = """
        import jax

        @jax.jit  # graftlint: disable=JX028  (one-shot capability probe)
        def probe(x):
            return x
    """
    assert "JX028" not in {f.rule
                           for f in lint_source(textwrap.dedent(src),
                                                _NN_PATH)}


# ---------------------------------------------------------------- JX029
def test_jx029_positive_fence_spellings_in_loops():
    # the three spellings: dotted through the jax alias, bare import,
    # and the array-method form — all inside for/while bodies
    src = """
        import jax
        from jax import block_until_ready

        def fit(batches, step):
            for x in batches:
                loss = step(x)
                jax.block_until_ready(loss)

        def drain(handles):
            while handles:
                block_until_ready(handles.pop())

        def decode(tokens, out):
            for t in tokens:
                out = out.block_until_ready()
            return out
    """
    fs = lint_source(textwrap.dedent(src), _NN_PATH)
    assert sum(f.rule == "JX029" for f in fs) == 3


def test_jx029_negative_outside_loop_profiler_and_tests():
    # a one-shot fence (no loop) never fires anywhere
    src_once = """
        import jax

        def probe(x):
            jax.block_until_ready(x)
            return x
    """
    assert "JX029" not in rules_at(src_once, _NN_PATH)
    # the sampled fence in the profiler, and test modules, are exempt
    src_loop = """
        import jax

        def fence_all(handles):
            for h in handles:
                jax.block_until_ready(h)
    """
    for path in ("deeplearning4j_tpu/observability/profiler.py",
                 "tests/test_fix.py", "tests/conftest.py"):
        assert "JX029" not in rules_at(src_loop, path)


def test_jx029_pragma_suppresses():
    src = """
        import jax

        def average(rounds):
            for avg in rounds:
                jax.block_until_ready(avg)  # graftlint: disable=JX029  (deliberate once-per-round timing sync)
    """
    assert "JX029" not in {f.rule
                           for f in lint_source(textwrap.dedent(src),
                                                _NN_PATH)}


# ---------------------------------------------------------------- JX030
def test_jx030_positive_tree_calls_and_comprehensions_in_loops():
    # dotted tree_util call, jax.tree short form, bare import, and the
    # params-like dict-comprehension rebuild — all inside loop bodies
    src = """
        import jax
        from jax.tree_util import tree_map

        def fit(batches, step, params):
            for x in batches:
                params = jax.tree_util.tree_map(lambda p: p, params)

        def drain(handles, grads):
            while handles:
                handles.pop()
                flat = jax.tree.leaves(grads)

        def refresh(workers, params):
            for w in workers:
                w.params = tree_map(lambda p: p + 0, params)

        def rebuild(batches, params):
            for x in batches:
                params = {k: v * 2 for k, v in params.items()}
    """
    fs = lint_source(textwrap.dedent(src), _NN_PATH)
    assert sum(f.rule == "JX030" for f in fs) == 4


def test_jx030_negative_header_once_per_fit_and_paths():
    # a loop HEADER traversal runs once — for x in tree_leaves(p) is the
    # canonical bytes-accounting idiom, not a per-step rebuild
    src_header = """
        import jax

        def nbytes(params):
            total = 0
            for l in jax.tree_util.tree_leaves(params):
                total += l.size
            return total
    """
    assert "JX030" not in rules_at(src_header, _NN_PATH)
    # outside a loop: placement happens once per fit
    src_once = """
        import jax

        def place(params, sharding):
            return jax.tree_util.tree_map(lambda p: p, params)
    """
    assert "JX030" not in rules_at(src_once, _NN_PATH)
    # hot-path scoping: the same loop body is legal outside nn//parallel/
    src_loop = """
        import jax

        def fold(rounds, params):
            for r in rounds:
                params = jax.tree_util.tree_map(lambda p: p, params)
    """
    for path in ("deeplearning4j_tpu/utils/fix.py",
                 "deeplearning4j_tpu/observability/fix.py",
                 "tests/test_fix.py"):
        assert "JX030" not in rules_at(src_loop, path)
    # a comprehension over a non-tree name stays silent
    src_other = """
        def fold(rounds, rows):
            for r in rounds:
                out = [c * 2 for c in rows]
    """
    assert "JX030" not in rules_at(src_other, _NN_PATH)


def test_jx030_pragma_suppresses():
    src = """
        import jax

        def average(rounds, params):
            for r in rounds:
                params = jax.tree_util.tree_map(lambda p: p, params)  # graftlint: disable=JX030  (once per averaging round, not per step)
    """
    assert "JX030" not in {f.rule
                           for f in lint_source(textwrap.dedent(src),
                                                _NN_PATH)}


# ---------------------------------------------------------------- JX031
def test_jx031_positive_per_block_transfers():
    # per-block device traffic in all three spellings: .item() per table
    # entry, device_put per block of a table-iterating loop, and
    # device_get subscripting the table inside a while loop
    src = """
        import jax
        import numpy as np

        def gather(tables, slot, n, kv):
            out = []
            for i in range(n):
                out.append(kv[tables[slot, i].item()])
            return out

        def upload(table_row, pool):
            for blk in table_row:
                jax.device_put(blk)

        def drain(tables, pending):
            while pending:
                pending.pop()
                row = jax.device_get(tables[0])
    """
    fs = lint_source(textwrap.dedent(src), _GENERATION_PATH)
    assert sum(f.rule == "JX031" for f in fs) == 3


def test_jx031_negative_whole_table_bookkeeping_and_paths():
    # the engine's contract: the WHOLE table ships once per program call
    # (outside any loop), and host-side allocator bookkeeping loops over
    # tables never touch the device — both stay silent
    src_ok = """
        import jax
        import numpy as np

        def step(fn, caches, tables, pos):
            return fn(caches, tables.copy(), pos.copy())

        def release(tables, slot, refs):
            for blk in tables[slot]:
                refs[int(blk)] -= 1
    """
    assert "JX031" not in rules_at(src_ok, _GENERATION_PATH)
    # a .item() in a loop NOT touching a table is JX023's business
    src_item = """
        import jax

        def emit(toks):
            for t in toks:
                yield t.item()
    """
    assert "JX031" not in rules_at(src_item, _GENERATION_PATH)
    # path scoping: identical per-block code outside generation/ (and in
    # generation tests) is out of scope
    src_loop = """
        import jax

        def upload(table_row):
            for blk in table_row:
                jax.device_put(blk)
    """
    for path in ("deeplearning4j_tpu/nn/fix.py",
                 "tests/test_generation.py"):
        assert "JX031" not in rules_at(src_loop, path)


def test_jx031_pragma_suppresses():
    src = """
        import jax

        def dump(tables, slot):
            rows = []
            for i in range(tables.shape[1]):
                rows.append(tables[slot, i].item())  # graftlint: disable=JX031  (debug dump tool, not the request path)
            return rows
    """
    assert "JX031" not in {f.rule
                           for f in lint_source(textwrap.dedent(src),
                                                _GENERATION_PATH)}


# ---------------------------------------------------------------- JX032
def test_jx032_positive_lock_held_dispatch():
    # three dispatch classes under three lock spellings: engine entry
    # point under self._lock, fleet-wide swap under a dotted fleet
    # lock, HTTP client verb under a session lock
    src = """
        class Router:
            def route(self, x):
                with self._lock:
                    return self.best.engine.predict(x)

            def roll(self, model):
                with self.fleet._fleet_lock:
                    for r in self.fleet.replicas:
                        r.engine.hot_swap(model)

            def relay(self, sess, body):
                with sess.lock:
                    return sess.client.post("/generate", body)
    """
    fs = lint_source(textwrap.dedent(src), _SERVING_PATH)
    assert sum(f.rule == "JX032" for f in fs) == 3


def test_jx032_negative_snapshot_then_dispatch_and_paths():
    # the fleet idiom: pick the replica under the lock, dispatch
    # outside it — and O(1) bookkeeping under the lock stays legal
    src_ok = """
        class Router:
            def route(self, x):
                with self._lock:
                    target = min(self.replicas, key=lambda r: r.load())
                    target.inflight += 1
                return target.engine.predict(x)

            def migrate(self, sess, state):
                with sess.lock:
                    sess.epoch += 1
                    sess.replica.engine.import_session(state)
    """
    assert "JX032" not in rules_at(src_ok, _SERVING_PATH)
    # a with block that is not a lock (file handle) is out of scope
    src_file = """
        class Snap:
            def dump(self, path):
                with open(path) as fh:
                    return self.engine.predict(fh.read())
    """
    assert "JX032" not in rules_at(src_file, _SERVING_PATH)
    # path scoping: identical code outside serving/ (and in serving
    # tests) is out of scope
    src_held = """
        class Router:
            def route(self, x):
                with self._lock:
                    return self.best.engine.predict(x)
    """
    for path in ("deeplearning4j_tpu/generation/fix.py",
                 "tests/test_serving.py"):
        assert "JX032" not in rules_at(src_held, path)


def test_jx032_pragma_suppresses():
    src = """
        class Router:
            def drain(self, x):
                with self._lock:
                    return self.solo.engine.predict(x)  # graftlint: disable=JX032  (single-replica drain mode, fleet already quiesced)
    """
    assert "JX032" not in {f.rule
                           for f in lint_source(textwrap.dedent(src),
                                                _SERVING_PATH)}


# ---------------------------------------------------------------- JX018
def test_jx018_positive_unguarded_increment_from_thread():
    got = findings("""
        import threading

        class Engine:
            def __init__(self):
                self.batches = 0
                self._t = threading.Thread(target=self._loop, daemon=True)
                self._t.start()

            def _loop(self):
                self._note()

            def _note(self):
                self.batches += 1        # dispatcher thread, no lock

            def stats(self):
                return self.batches      # caller thread
    """, select=["JX018"])
    assert len(got) == 1 and got[0].rule == "JX018"


def test_jx018_positive_inconsistent_guarding():
    # guarded write in one method, bare write in another: the discipline
    # exists and this mutation skips it
    assert "JX018" in rules_of("""
        import threading

        class Holder:
            def __init__(self):
                self._lock = threading.Lock()
                self.version = 0
                t = threading.Thread(target=self._loop, daemon=True)
                t.start()

            def _loop(self):
                with self._lock:
                    self.version += 1

            def reset(self):
                self.version = 0         # skips the lock others hold
    """)


def test_jx018_negative_consistent_guard_and_no_threads():
    assert "JX018" not in rules_of("""
        import threading

        class Guarded:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0
                t = threading.Thread(target=self._loop, daemon=True)
                t.start()

            def _loop(self):
                with self._lock:
                    self.n += 1

            def read(self):
                with self._lock:
                    return self.n

        class SingleThreaded:
            def __init__(self):
                self.n = 0

            def bump(self):
                self.n += 1              # no thread entry: legal
    """)


def test_jx018_negative_aliased_import_and_injected_lock():
    # lock recognition must resolve `import threading as th` exactly like
    # spawn detection does, and an injected lock (ctor parameter) is a
    # lock because it is USED as one — neither may fire on guarded code
    assert "JX018" not in rules_of("""
        import threading as th

        class AliasGuarded:
            def __init__(self):
                self._lock = th.Lock()
                self.n = 0
                self._t = None

            def start(self):
                self._t = th.Thread(target=self._run, daemon=True)
                self._t.start()

            def _run(self):
                with self._lock:
                    self.n += 1

            def read(self):
                with self._lock:
                    return self.n

        class InjectedLock:
            def __init__(self, lock):
                self._lock = lock
                self.n = 0
                self._t = None

            def start(self):
                import threading
                self._t = threading.Thread(target=self._run, daemon=True)
                self._t.start()

            def _run(self):
                with self._lock:
                    self.n += 1

            def read(self):
                with self._lock:
                    return self.n
    """)


def test_jx020_positive_under_aliased_import():
    # the lock-order graph must see th.Lock() attrs or aliased modules
    # silently disable deadlock detection
    assert "JX020" in rules_of("""
        import threading as th

        class AB:
            def __init__(self):
                self.a = th.Lock()
                self.b = th.Lock()

            def fwd(self):
                with self.a:
                    with self.b:
                        pass

            def bwd(self):
                with self.b:
                    with self.a:
                        pass
    """)


def test_jx018_negative_thread_private_and_safe_attrs():
    assert "JX018" not in rules_of("""
        import queue
        import threading

        class Private:
            def __init__(self):
                self.progress = 0
                self.results = queue.Queue(8)   # thread-safe primitive
                t = threading.Thread(target=self._loop, daemon=True)
                t.start()

            def _loop(self):
                self.progress += 1       # only the thread touches it
                self.results.put(1)
    """)


def test_jx018_positive_handler_shared_server_counter():
    # handler classes run one instance per connection: `self` is private
    # but the server ref is shared across concurrent request threads
    assert "JX018" in rules_of("""
        class _H(JsonHandler):
            server_ref = None

            def do_POST(self):
                srv = self.server_ref
                srv.failures += 1
    """)


def test_jx018_negative_handler_local_receiver():
    # a receiver built fresh in the handler is single-threaded
    assert "JX018" not in rules_of("""
        class _H(JsonHandler):
            def do_POST(self):
                r = Reader(self.rfile)
                r.off += 4
    """)


def test_jx018_pragma_suppresses():
    assert "JX018" not in rules_of("""
        import threading

        class E:
            def __init__(self):
                t = threading.Thread(target=self._loop, daemon=True)
                t.start()

            def _loop(self):
                self.n += 1  # graftlint: disable=JX018  (monotonic, torn reads fine)

            def read(self):
                return self.n
    """)


# ---------------------------------------------------------------- JX019
def test_jx019_positive_self_attr_thread_never_joined():
    got = findings("""
        import threading

        class Pump:
            def start(self):
                self._t = threading.Thread(target=self._run)
                self._t.start()

            def _run(self):
                pass

            def close(self):
                pass                     # no join anywhere
    """, select=["JX019"])
    assert len(got) == 1


def test_jx019_positive_local_thread_and_chained_start():
    got = findings("""
        import threading

        def fire_and_forget(fn):
            t = threading.Thread(target=fn)
            t.start()                    # local, never joined

        def also_leaks(fn):
            threading.Thread(target=fn).start()   # unbound handle
    """, select=["JX019"])
    assert len(got) == 2


def test_jx019_positive_timer_without_cancel():
    assert "JX019" in rules_of("""
        import threading

        class Delayed:
            def arm(self):
                self._timer = threading.Timer(5.0, self._fire)
                self._timer.start()

            def _fire(self):
                pass
    """)


def test_jx019_negative_daemon_joined_escaping_and_submit():
    assert "JX019" not in rules_of("""
        import threading

        class Clean:
            def start(self):
                self._t = threading.Thread(target=self._run, daemon=True)
                self._t.start()
                self._w = threading.Thread(target=self._run)
                self._w.start()

            def _run(self):
                pass

            def close(self):
                self._w.join()

        def handed_to_caller(fn):
            t = threading.Thread(target=fn)
            t.start()
            return t                     # caller's to join

        def pooled(pool, fn):
            pool.submit(fn)              # executor owns the lifecycle
    """)


def test_jx019_negative_computed_daemon_flag_is_unresolvable():
    # daemon=<expr> can't be resolved statically: the fact drops on the
    # quiet side (possibly-daemon), never a loud false positive
    assert "JX019" not in rules_of("""
        import threading

        class Configurable:
            def __init__(self, cfg):
                self._cfg = cfg

            def start(self, flag):
                self._t = threading.Thread(target=self._run,
                                           daemon=flag)
                self._t.start()
                self._u = threading.Thread(target=self._run)
                self._u.daemon = self._cfg.daemonize
                self._u.start()

            def _run(self):
                pass
    """)


def test_jx019_negative_double_buffer_alias_join():
    # the CheckpointManager idiom: the handle swaps through a local
    # before joining — still a join on the teardown path
    assert "JX019" not in rules_of("""
        import threading

        class Writer:
            def save(self):
                t = threading.Thread(target=self._write)
                self._worker = t
                t.start()

            def _write(self):
                pass

            def wait(self):
                t, self._worker = self._worker, None
                if t is not None:
                    t.join()
    """)


def test_jx019_pragma_suppresses():
    assert "JX019" not in rules_of("""
        import threading

        def spin(fn):
            t = threading.Thread(target=fn)  # graftlint: disable=JX019  (process-lifetime pump)
            t.start()
    """)


# ---------------------------------------------------------------- JX020
def test_jx020_positive_opposite_nesting_same_class():
    got = findings("""
        import threading

        class Transfer:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def debit(self):
                with self._a:
                    with self._b:
                        pass

            def credit(self):
                with self._b:
                    with self._a:
                        pass
    """, select=["JX020"])
    assert len(got) == 1
    assert "cycle" in got[0].message


def test_jx020_positive_cross_class_one_hop_call():
    # A holds its lock while calling into B (which takes B's lock); B
    # holds its lock while calling back into A — opposite orders across
    # two classes, resolved through constructor-typed attributes
    assert "JX020" in rules_of("""
        import threading

        class A:
            def __init__(self):
                self._la = threading.Lock()
                self._b = B()

            def fwd(self):
                with self._la:
                    self._b.take_b()

            def take_a(self):
                with self._la:
                    pass

        class B:
            def __init__(self):
                self._lb = threading.Lock()
                self._a = A()

            def take_b(self):
                with self._lb:
                    pass

            def back(self):
                with self._lb:
                    self._a.take_a()
    """)


def test_jx020_negative_consistent_order_and_single_lock():
    assert "JX020" not in rules_of("""
        import threading

        class Ordered:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                with self._a:
                    with self._b:
                        pass

            def two(self):
                with self._a:
                    with self._b:
                        pass

            def three(self):
                with self._b:
                    pass                 # alone: no edge back
    """)


# ---------------------------------------------------------------- JX021
def test_jx021_positive_membership_outside_guard():
    got = findings("""
        import threading

        class Cache:
            def __init__(self):
                self._lock = threading.Lock()
                self._d = {}

            def put(self, k, v):
                with self._lock:
                    self._d[k] = v

            def get(self, k):
                if k in self._d:         # unguarded check...
                    return self._d[k]    # ...then act
                return None
    """, select=["JX021"])
    assert len(got) == 1


def test_jx021_negative_pair_under_guard_or_no_discipline():
    assert "JX021" not in rules_of("""
        import threading

        class Guarded:
            def __init__(self):
                self._lock = threading.Lock()
                self._d = {}

            def put(self, k, v):
                with self._lock:
                    self._d[k] = v

            def get(self, k):
                with self._lock:
                    if k in self._d:
                        return self._d[k]
                return None

        class NoLocks:
            def __init__(self):
                self._d = {}

            def get(self, k):
                if k in self._d:         # no inferred guard: no
                    return self._d[k]    # discipline to violate
                return None
    """)


def test_jx021_positive_qsize_gated_get():
    assert "JX021" in rules_of("""
        import queue
        import threading

        class Drain:
            def __init__(self):
                self._q = queue.Queue(8)
                t = threading.Thread(target=self._run, daemon=True)
                t.start()

            def _run(self):
                pass

            def take(self):
                if not self._q.empty():
                    return self._q.get()   # sibling consumer can win
                return None
    """)


def test_jx021_negative_get_nowait_drain():
    assert "JX021" not in rules_of("""
        import queue
        import threading

        class Drain:
            def __init__(self):
                self._q = queue.Queue(8)
                t = threading.Thread(target=self._run, daemon=True)
                t.start()

            def _run(self):
                pass

            def take(self):
                try:
                    return self._q.get_nowait()
                except queue.Empty:
                    return None
    """)


def test_jx021_pragma_suppresses():
    assert "JX021" not in rules_of("""
        import threading

        class Cache:
            def __init__(self):
                self._lock = threading.Lock()
                self._d = {}

            def put(self, k, v):
                with self._lock:
                    self._d[k] = v

            def get(self, k):
                if k in self._d:  # graftlint: disable=JX021  (single-threaded reader)
                    return self._d[k]
                return None
    """)


# ------------------------------------ whole-program analysis layer units
def _program_of(src: str, path: str = "mod.py"):
    from tools.graftlint.analysis import analyze_module
    from tools.graftlint.program import build_program
    return build_program([analyze_module(textwrap.dedent(src), path)])


def _entries(prog, cls_name: str):
    cls = next(c for c in prog.classes if c.name == cls_name)
    return {getattr(f, "name", "<lambda>") for f in cls.entry_funcs}


def test_thread_entry_direct_target_and_closure():
    prog = _program_of("""
        import threading

        class W:
            def go(self):
                threading.Thread(target=self._loop).start()

            def _loop(self):
                self._helper()

            def _helper(self):
                pass

            def untouched(self):
                pass
    """)
    assert _entries(prog, "W") == {"_loop", "_helper"}


def test_thread_entry_bound_method_one_hop_wrapper_and_submit():
    prog = _program_of("""
        import threading

        class W:
            def a(self):
                fn = self._loop_a        # one-hop alias
                threading.Thread(target=fn).start()

            def b(self, pool):
                pool.submit(self._loop_b)

            def c(self):
                def runner():
                    self._loop_c()
                t = threading.Timer(1.0, runner)
                t.start()
                t.cancel()

            def _loop_a(self):
                pass

            def _loop_b(self):
                pass

            def _loop_c(self):
                pass
    """)
    got = _entries(prog, "W")
    assert {"_loop_a", "_loop_b", "_loop_c", "runner"} <= got


def test_thread_entry_cross_class_constructor_typed():
    prog = _program_of("""
        import threading

        class Worker:
            def run(self):
                self.steps = 1

            def idle(self):
                pass

        def launch():
            w = Worker()
            threading.Thread(target=w.run).start()
    """)
    assert _entries(prog, "Worker") == {"run"}


def test_guarded_by_with_scope_and_try_finally():
    prog = _program_of("""
        import threading

        class G:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0
                self.m = 0

            def with_scope(self):
                with self._lock:
                    self.n += 1

            def try_finally(self):
                self._lock.acquire()
                try:
                    self.m += 1
                finally:
                    self._lock.release()

            def after_release(self):
                self._lock.acquire()
                self._lock.release()
                self.m += 1              # NOT guarded here
    """)
    cls = prog.classes[0]
    assert cls.guards("n") == {"_lock"}
    assert cls.guards("m") == {"_lock"}
    unguarded_m = [a for a in cls.accesses
                   if a.attr == "m" and a.write and not a.held
                   and not a.in_init]
    assert len(unguarded_m) == 1         # only the after-release write


def test_guarded_by_property_aliased_lock():
    prog = _program_of("""
        import threading

        class G:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            @property
            def lock(self):
                return self._lock

            def bump(self):
                with self.lock:          # alias guards the same token
                    self.n += 1
    """)
    assert prog.classes[0].guards("n") == {"_lock"}


def test_lock_order_graph_edges_and_cycle_detection():
    from tools.graftlint.program import find_lock_cycles
    prog = _program_of("""
        import threading

        class T:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def fwd(self):
                with self._a:
                    with self._b:
                        pass

            def rev(self):
                with self._b:
                    with self._a:
                        pass
    """)
    edges = prog.lock_edges()
    labels = {(a.label(), b.label()) for a, b, _, _ in edges}
    assert ("T._a", "T._b") in labels and ("T._b", "T._a") in labels
    cycles = find_lock_cycles(edges)
    assert len(cycles) == 1
    assert {n.label() for n in cycles[0][0]} == {"T._a", "T._b"}


def test_lock_order_no_cycle_negative():
    from tools.graftlint.program import find_lock_cycles
    prog = _program_of("""
        import threading

        class T:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()
                self._c = threading.Lock()

            def chain(self):
                with self._a:
                    with self._b:
                        with self._c:
                            pass
    """)
    assert find_lock_cycles(prog.lock_edges()) == []


# ------------------------------------------------------------- pragmas
def test_pragma_same_line_suppresses():
    assert "JX007" not in rules_of("""
        def f():
            try:
                return 1
            except:  # graftlint: disable=JX007
                return 2
    """)


def test_pragma_standalone_line_suppresses_next_line():
    assert "JX008" not in rules_of("""
        # graftlint: disable=JX008
        def f(a, xs=[]):
            return a
    """)


def test_pragma_disable_file():
    src = """
        # graftlint: disable-file=JX007
        def f():
            try:
                return 1
            except:
                return 2

        def g():
            try:
                return 3
            except:
                return 4
    """
    assert "JX007" not in rules_of(src)


def test_pragma_only_suppresses_named_rule():
    got = rules_of("""
        def f(a, xs=[]):
            try:
                return a
            except:  # graftlint: disable=JX008
                return xs
    """)
    assert "JX007" in got        # pragma names the WRONG rule
    assert "JX008" in got        # JX008 is on the def line, not here


# ------------------------------------------------------------- baseline
def test_baseline_absorbs_exact_budget(tmp_path):
    src = textwrap.dedent("""
        def f():
            try:
                return 1
            except:
                return 2
    """)
    f = tmp_path / "m.py"
    f.write_text(src)
    found = lint_paths([str(f)])
    assert [x.rule for x in found] == ["JX007"]
    bl = Baseline.from_findings(found)
    assert bl.filter(found) == []
    # a SECOND bare except exceeds the budget
    f.write_text(src + textwrap.dedent("""
        def g():
            try:
                return 3
            except:
                return 4
    """))
    found2 = lint_paths([str(f)])
    assert len(found2) == 2
    assert len(bl.filter(found2)) == 1


def test_baseline_round_trips_through_json(tmp_path):
    bl = Baseline({"pkg/m.py::JX003": 2})
    p = tmp_path / "baseline.json"
    bl.save(str(p))
    loaded = Baseline.load(str(p))
    assert loaded.allowances == {"pkg/m.py::JX003": 2}
    assert Baseline.load(str(tmp_path / "missing.json")).allowances == {}


def test_baseline_reports_stale_entries(tmp_path):
    """Ratchet: allowances matching no finding come back as stale so the
    suppression can't outlive its bug and silently absorb a new one."""
    src = textwrap.dedent("""
        def f():
            try:
                return 1
            except:
                return 2
    """)
    f = tmp_path / "m.py"
    f.write_text(src)
    found = lint_paths([str(f)])
    import os
    key = f"{os.path.relpath(found[0].path)}::JX007".replace(os.sep, "/")
    live = Baseline({key: 1, "gone/file.py::JX003": 2})
    kept, stale = live.apply(found)
    assert kept == []
    assert stale == ["gone/file.py::JX003"]
    # an entry matching SOME findings is live even when over-budgeted
    over = Baseline({key: 5})
    kept, stale = over.apply(found)
    assert kept == [] and stale == []


def test_cli_stale_baseline_errors(tmp_path):
    # run FROM tmp_path so the fabricated key's path resolves against
    # the cwd, the way real repo-root runs resolve repo-relative keys
    clean = tmp_path / "ok.py"
    clean.write_text("def f(a, xs=None):\n    return a\n")
    bl = tmp_path / "baseline.json"
    Baseline({"gone/file.py::JX008": 1}).save(str(bl))
    env = dict(os.environ, PYTHONPATH=str(REPO_ROOT))
    r = subprocess.run(
        [sys.executable, "-m", "tools.graftlint",
         "--baseline", str(bl), "ok.py"],
        capture_output=True, text=True, cwd=str(tmp_path), env=env)
    assert r.returncode == 2
    assert "stale baseline" in r.stderr
    assert "gone/file.py::JX008" in r.stderr


def test_cli_stale_ratchet_stands_down_outside_baseline_cwd(tmp_path):
    """Baseline keys are relative to the cwd they were written from; a
    run from a DIFFERENT directory cannot resolve them, so live
    allowances must not be escalated into exit-2 'stale' errors."""
    proj = tmp_path / "proj"
    proj.mkdir()
    f = proj / "m.py"
    f.write_text("def f(a, xs=[]):\n    return a\n")   # JX008 finding
    bl = proj / "baseline.json"
    # the live m.py key proves the cwd mismatch, which must also shield
    # the deleted-file key from being misjudged through the wrong cwd
    Baseline({"m.py::JX008": 1, "gone.py::JX019": 1}).save(str(bl))
    env = dict(os.environ, PYTHONPATH=str(REPO_ROOT))
    elsewhere = tmp_path / "elsewhere"
    elsewhere.mkdir()
    r = subprocess.run(
        [sys.executable, "-m", "tools.graftlint",
         "--baseline", str(bl), str(proj)],
        capture_output=True, text=True, cwd=str(elsewhere), env=env)
    # the allowance can't absorb its finding from this cwd (findings
    # report, exit 1) — but it is live, not stale: no exit-2 escalation
    assert r.returncode == 1, r.stderr
    assert "stale" not in r.stderr


def test_cli_stale_ratchet_resolves_unlinted_keys_at_baseline_root(
        tmp_path):
    """An allowance for a file OUTSIDE the linted subset must be judged
    against the baseline's own root: from a parent-dir cwd a live file
    used to read as deleted (bogus exit 2), while a genuinely deleted
    file must still ratchet from any cwd."""
    proj = tmp_path / "proj"
    proj.mkdir()
    (proj / "m.py").write_text("x = 1\n")
    (proj / "other.py").write_text("def f(a, xs=[]):\n    return a\n")
    bl = proj / "baseline.json"
    Baseline({"other.py::JX008": 1, "gone.py::JX019": 1}).save(str(bl))
    env = dict(os.environ, PYTHONPATH=str(REPO_ROOT))
    # lint ONLY m.py, from the parent directory
    r = subprocess.run(
        [sys.executable, "-m", "tools.graftlint",
         "--baseline", str(bl), "proj/m.py"],
        capture_output=True, text=True, cwd=str(tmp_path), env=env)
    assert r.returncode == 2, (r.stdout, r.stderr)
    assert "gone.py::JX019" in r.stderr      # deleted: ratchets anywhere
    assert "other.py::JX008" not in r.stderr  # live: never misread


def test_cli_stale_ratchet_skipped_on_rule_subsets(tmp_path):
    """--select/--ignore runs never execute the rules some allowances
    target, so they must not classify those allowances as stale."""
    f = tmp_path / "m.py"
    f.write_text("def f(a, xs=[]):\n    return a\n")   # JX008 finding
    bl = tmp_path / "baseline.json"
    Baseline.from_findings(lint_paths([str(f)])).save(str(bl))
    # full run: allowance matches, clean exit
    r = subprocess.run(
        [sys.executable, "-m", "tools.graftlint",
         "--baseline", str(bl), str(f)],
        capture_output=True, text=True, cwd=str(REPO_ROOT))
    assert r.returncode == 0, r.stderr
    # subset run that never executes JX008: the allowance matches
    # nothing, but must NOT be reported stale
    r = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "--select", "JX007",
         "--baseline", str(bl), str(f)],
        capture_output=True, text=True, cwd=str(REPO_ROOT))
    assert r.returncode == 0, r.stderr
    assert "stale" not in r.stderr


def test_cli_write_baseline_rejects_changed_only(tmp_path):
    f = tmp_path / "m.py"
    f.write_text("x = 1\n")
    r = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "--write-baseline",
         "--changed-only", "HEAD", str(f)],
        capture_output=True, text=True, cwd=str(REPO_ROOT))
    assert r.returncode == 2
    assert "full run" in r.stderr


# ------------------------------------------------------------------ CLI
def test_cli_text_and_json_and_exit_codes(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("def f(a, xs=[]):\n    return a\n")
    env_root = str(REPO_ROOT)
    r = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "--no-baseline", str(bad)],
        capture_output=True, text=True, cwd=env_root)
    assert r.returncode == 1
    assert "JX008" in r.stdout
    r = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "--no-baseline",
         "--format", "json", str(bad)],
        capture_output=True, text=True, cwd=env_root)
    data = json.loads(r.stdout)
    assert data and data[0]["rule"] == "JX008"
    good = tmp_path / "good.py"
    good.write_text("def f(a, xs=None):\n    return a\n")
    r = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "--no-baseline", str(good)],
        capture_output=True, text=True, cwd=env_root)
    assert r.returncode == 0
    assert "clean" in r.stdout


def test_syntax_error_reported_not_crashed():
    got = lint_source("def f(:\n", "broken.py")
    assert [f.rule for f in got] == ["JX000"]


def test_cli_sarif_output(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("def f(a, xs=[]):\n    return a\n")
    r = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "--no-baseline",
         "--format", "sarif", str(bad)],
        capture_output=True, text=True, cwd=str(REPO_ROOT))
    assert r.returncode == 1
    doc = json.loads(r.stdout)
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "graftlint"
    assert [rule["id"] for rule in run["tool"]["driver"]["rules"]] == ["JX008"]
    res = run["results"][0]
    assert res["ruleId"] == "JX008"
    loc = res["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].endswith("bad.py")
    assert loc["region"]["startLine"] == 1
    # clean run: valid SARIF with zero results, exit 0
    good = tmp_path / "good.py"
    good.write_text("x = 1\n")
    r = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "--no-baseline",
         "--format", "sarif", str(good)],
        capture_output=True, text=True, cwd=str(REPO_ROOT))
    assert r.returncode == 0
    assert json.loads(r.stdout)["runs"][0]["results"] == []


def test_cli_changed_only_lints_only_changed_files(tmp_path):
    """CI fast path: --changed-only <ref> restricts linting to files
    changed vs the ref (plus untracked), so a PR touching one module
    doesn't re-lint the world on every push."""
    env = {"GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@t",
           "GIT_COMMITTER_NAME": "t", "GIT_COMMITTER_EMAIL": "t@t",
           "PATH": subprocess.os.environ["PATH"],
           "HOME": str(tmp_path),
           # the CLI resolves git against the LINTED tree (the tmp
           # repo), so the linter package must come in via PYTHONPATH
           "PYTHONPATH": str(REPO_ROOT)}

    def git(*args):
        r = subprocess.run(["git", *args], cwd=str(tmp_path), env=env,
                           capture_output=True, text=True)
        assert r.returncode == 0, r.stderr
        return r

    git("init", "-q")
    (tmp_path / "stable.py").write_text("def f(a, xs=[]):\n    return a\n")
    (tmp_path / "touched.py").write_text("x = 1\n")
    git("add", "-A")
    git("commit", "-qm", "seed")
    # change one committed file, add one untracked file — both with
    # findings; the stable (committed, unchanged) file also has one
    (tmp_path / "touched.py").write_text("def g(b, m={}):\n    return b\n")
    (tmp_path / "fresh.py").write_text("def h(c, s=set()):\n    return c\n")
    r = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "--no-baseline",
         "--changed-only", "HEAD", "--format", "json",
         str(tmp_path)],
        capture_output=True, text=True, cwd=str(tmp_path), env=env)
    data = json.loads(r.stdout)
    hit_files = {Path(d["path"]).name for d in data}
    assert hit_files == {"touched.py", "fresh.py"}
    assert all(d["rule"] == "JX008" for d in data)
    # from a SUBDIRECTORY the same set must be found: ls-files scopes to
    # its cwd, so the CLI roots both git commands at the repo toplevel
    sub = tmp_path / "sub"
    sub.mkdir()
    r = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "--no-baseline",
         "--changed-only", "HEAD", "--format", "json", str(tmp_path)],
        capture_output=True, text=True, cwd=str(sub), env=env)
    data = json.loads(r.stdout)
    assert {Path(d["path"]).name for d in data} == {"touched.py",
                                                    "fresh.py"}
    # from inside a DIFFERENT git repo: git must be anchored at the
    # linted tree, not the cwd — resolving the cwd's repo used to diff
    # the wrong repo, intersect nothing, and report a false "clean"
    r = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "--no-baseline",
         "--changed-only", "HEAD", "--format", "json", str(tmp_path)],
        capture_output=True, text=True, cwd=str(REPO_ROOT), env=env)
    data = json.loads(r.stdout)
    assert {Path(d["path"]).name for d in data} == {"touched.py",
                                                    "fresh.py"}
    # with nothing changed, the run is clean without linting anything
    git("add", "-A")
    git("commit", "-qm", "all in")
    r = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "--no-baseline",
         "--changed-only", "HEAD", str(tmp_path)],
        capture_output=True, text=True, cwd=str(tmp_path), env=env)
    assert r.returncode == 0
    assert "no changed" in r.stdout


# ------------------------------------------------------------- the gate
def test_every_rule_has_docs():
    assert set(RULES) | set(PROGRAM_RULES) == set(RULE_DOCS)
    assert not set(RULES) & set(PROGRAM_RULES)
    assert len(RULES) == 28
    assert len(PROGRAM_RULES) == 4


@pytest.fixture(scope="module")
def package_lint():
    """ONE timed full-package run shared by the gate, ratchet, and
    wall-time budget tests (the run itself is the expensive part)."""
    t0 = time.perf_counter()
    found = lint_paths([str(PKG)])
    elapsed = time.perf_counter() - t0
    return found, elapsed


def test_package_is_clean_modulo_baseline(package_lint):
    """THE tier-1 gate: every future PR re-lints the whole package."""
    found, _ = package_lint
    kept, _stale = Baseline.load(str(BASELINE)).apply(found)
    assert kept == [], "\n".join(f.format() for f in kept)


def test_package_baseline_has_no_stale_entries(package_lint):
    """The ratchet: a baseline entry matching no finding means the
    suppressed bug was fixed — the allowance must be deleted."""
    found, _ = package_lint
    _, stale = Baseline.load(str(BASELINE)).apply(found)
    assert stale == [], stale


def test_full_package_lint_within_time_budget(package_lint):
    """The linter is part of the developer loop (tier-1 + bench): a rule
    addition that blows up wall time is a regression.  The budget is ~6x
    the current measured full-package time, so it trips on complexity
    blowups (quadratic walks), not CI jitter."""
    _, elapsed = package_lint
    assert elapsed < 25.0, f"full-package graftlint took {elapsed:.1f}s"


def test_baseline_is_near_empty():
    """The checked-in baseline must stay justified-in-review small."""
    bl = Baseline.load(str(BASELINE))
    assert sum(bl.allowances.values()) <= 5, bl.allowances


def test_no_bare_except_in_package():
    """ISSUE 1 acceptance: zero bare `except:` clauses in the package."""
    found = [f for f in lint_paths([str(PKG)], select=["JX007"])]
    assert found == [], "\n".join(f.format() for f in found)


# ----------------------------------------------- review-hardening fixes
def test_pragma_allows_trailing_justification():
    """The documented pragma form carries a justifying comment after the
    code list; it must still suppress."""
    assert "JX007" not in rules_of("""
        def f():
            try:
                return 1
            except:  # graftlint: disable=JX007   (cleanup must never raise)
                return 2
    """)
    assert "JX008" not in rules_of("""
        def f(a, xs=[], m={}):  # graftlint: disable=JX008, JX007 shared cache
            return a
    """)


def test_nonexistent_path_errors_instead_of_clean(tmp_path):
    with pytest.raises(FileNotFoundError):
        lint_paths([str(tmp_path / "no_such_dir")])


def test_non_py_file_argument_errors(tmp_path):
    f = tmp_path / "notes.txt"
    f.write_text("hello")
    with pytest.raises(ValueError, match="not a .py file"):
        lint_paths([str(f)])


def test_unknown_select_code_errors():
    with pytest.raises(ValueError, match="unknown rule code"):
        lint_source("x = 1\n", "m.py", select=["JXBOGUS"])
    with pytest.raises(ValueError, match="unknown rule code"):
        lint_source("x = 1\n", "m.py", ignore=["JX03"])


def test_cli_typo_path_exits_nonzero(tmp_path):
    r = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", str(tmp_path / "typo_dir")],
        capture_output=True, text=True, cwd=str(REPO_ROOT))
    assert r.returncode == 2
    assert "no such file" in r.stderr


def test_ui_numeric_style_fields_escaped_on_wire():
    """Declared-numeric style fields are NOT type-checked by the serde,
    so a string riding in where an int is expected must still escape."""
    from deeplearning4j_tpu.ui import (ComponentDiv, StyleDiv,
                                       component_from_json,
                                       component_to_json)
    payload = '"><script>alert(1)</script>'
    d = ComponentDiv(style=StyleDiv(width=100, float_value=payload))
    wire = component_to_json(d)
    out = component_from_json(wire).render()
    assert "<script>" not in out
    assert "&quot;&gt;&lt;script&gt;" in out
    # string smuggled into a declared-int field over the wire
    wire2 = wire.replace("100", json.dumps(payload).strip('"') and
                         json.dumps(payload))
    out2 = component_from_json(wire2).render()
    assert "<script>" not in out2
