"""MultiLayerNetwork end-to-end: training reduces loss, evaluation works,
gradient checks pass (the reference's primary correctness oracle).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                NeuralNetConfiguration)
from deeplearning4j_tpu.data.dataset import INDArrayDataSetIterator
from deeplearning4j_tpu.data.mnist import IrisDataSetIterator, MnistDataSetIterator
from deeplearning4j_tpu.nn.conf.updaters import Adam, Nesterovs, NoOp, Sgd
from deeplearning4j_tpu.nn.layers.feedforward import (ActivationLayer,
                                                      DenseLayer,
                                                      DropoutLayer,
                                                      EmbeddingLayer,
                                                      LossLayer, OutputLayer)
from deeplearning4j_tpu.train.listeners import (CollectScoresIterationListener,
                                                ScoreIterationListener)
from deeplearning4j_tpu.utils.gradient_check import check_gradients


def iris_net(updater=None, seed=42, **defaults):
    b = (NeuralNetConfiguration.builder()
         .seed(seed)
         .updater(updater or Adam(learning_rate=0.02)))
    conf = (b.list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    return MultiLayerNetwork(conf).init()


def test_fit_reduces_score_iris():
    net = iris_net()
    it = IrisDataSetIterator(batch_size=50)
    ds = next(iter(it))
    s0 = net.score(x=ds.features, y=ds.labels)
    collector = CollectScoresIterationListener()
    net.set_listeners(collector)
    net.fit(it, epochs=60)
    s1 = net.score(x=ds.features, y=ds.labels)
    assert s1 < s0 * 0.5
    assert len(collector.scores) > 0


def test_evaluate_iris_accuracy():
    net = iris_net()
    it = IrisDataSetIterator(batch_size=150)
    net.fit(it, epochs=120)
    ev = net.evaluate(it)
    assert ev.accuracy() > 0.9
    assert 0.0 <= ev.f1() <= 1.0
    assert "Accuracy" in ev.stats()


def test_mnist_mlp_learns():
    train = MnistDataSetIterator(batch_size=128, train=True, num_examples=2048)
    test = MnistDataSetIterator(batch_size=256, train=False, num_examples=512)
    conf = (NeuralNetConfiguration.builder()
            .seed(12345)
            .updater(Adam(learning_rate=1e-3))
            .list()
            .layer(DenseLayer(n_out=64, activation="relu"))
            .layer(OutputLayer(n_out=10, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(784))
            .build())
    net = MultiLayerNetwork(conf).init()
    net.fit(train, epochs=3)
    ev = net.evaluate(test)
    assert ev.accuracy() > 0.6  # synthetic blobs are easy; real MNIST also passes


def test_output_shape_and_softmax():
    net = iris_net()
    x = np.random.default_rng(0).standard_normal((7, 4)).astype(np.float32)
    out = np.asarray(net.output(x))
    assert out.shape == (7, 3)
    assert np.allclose(out.sum(axis=1), 1.0, atol=1e-5)


def test_gradient_check_dense_mcxent():
    conf = (NeuralNetConfiguration.builder()
            .seed(7)
            .updater(Sgd(learning_rate=0.1))
            .dtype("float64")
            .list()
            .layer(DenseLayer(n_out=6, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(5))
            .build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    x = rng.standard_normal((4, 5))
    y = np.eye(3)[rng.integers(0, 3, 4)]
    assert check_gradients(net, x, y)


@pytest.mark.parametrize("loss,act,out_dim", [
    ("mse", "identity", 4),
    ("mae", "tanh", 3),
    ("xent", "sigmoid", 2),
    ("hinge", "identity", 1),
])
def test_gradient_check_losses(loss, act, out_dim):
    conf = (NeuralNetConfiguration.builder()
            .seed(7)
            .updater(Sgd(learning_rate=0.1))
            .dtype("float64")
            .list()
            .layer(DenseLayer(n_out=5, activation="sigmoid"))
            .layer(OutputLayer(n_out=out_dim, activation=act, loss=loss))
            .set_input_type(InputType.feed_forward(3))
            .build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(1)
    x = rng.standard_normal((5, 3))
    if loss in ("xent", "hinge"):
        y = rng.integers(0, 2, (5, out_dim)).astype(float)
    else:
        y = rng.standard_normal((5, out_dim))
    assert check_gradients(net, x, y)


def test_gradient_check_with_l1_l2():
    conf = (NeuralNetConfiguration.builder()
            .seed(7)
            .updater(Sgd(learning_rate=0.1))
            .l1(0.01).l2(0.02)
            .dtype("float64")
            .list()
            .layer(DenseLayer(n_out=4, activation="tanh"))
            .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(3))
            .build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(2)
    x = rng.standard_normal((4, 3)) * 2
    y = np.eye(2)[rng.integers(0, 2, 4)]
    assert check_gradients(net, x, y)


def test_per_layer_updater_override():
    conf = (NeuralNetConfiguration.builder()
            .seed(3)
            .updater(Adam(learning_rate=0.01))
            .list()
            .layer(DenseLayer(n_out=8, activation="relu",
                              updater=Nesterovs(learning_rate=0.05)))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent",
                               updater=NoOp()))
            .set_input_type(InputType.feed_forward(4))
            .build())
    net = MultiLayerNetwork(conf).init()
    w_out_before = np.asarray(net.params["layer_1"]["W"]).copy()
    w_hid_before = np.asarray(net.params["layer_0"]["W"]).copy()
    it = IrisDataSetIterator(batch_size=150)
    net.fit(it, epochs=2)
    # NoOp layer frozen, other layer trained
    assert np.allclose(np.asarray(net.params["layer_1"]["W"]), w_out_before)
    assert not np.allclose(np.asarray(net.params["layer_0"]["W"]), w_hid_before)


def test_dropout_and_activation_layers():
    conf = (NeuralNetConfiguration.builder()
            .seed(3)
            .updater(Sgd(learning_rate=0.05))
            .list()
            .layer(DenseLayer(n_out=16))
            .layer(ActivationLayer(activation="relu"))
            .layer(DropoutLayer(dropout=0.5))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    net = MultiLayerNetwork(conf).init()
    it = IrisDataSetIterator(batch_size=50)
    net.fit(it, epochs=3)
    out = np.asarray(net.output(np.zeros((2, 4), np.float32)))
    assert out.shape == (2, 3)


def test_embedding_layer():
    conf = (NeuralNetConfiguration.builder()
            .seed(3)
            .updater(Adam(learning_rate=0.05))
            .list()
            .layer(EmbeddingLayer(n_in=20, n_out=8))
            .layer(OutputLayer(n_out=4, activation="softmax", loss="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    idx = rng.integers(0, 20, (16, 1)).astype(np.int32)
    y = np.eye(4)[idx[:, 0] % 4]
    s0 = net.score(x=idx, y=y)
    for _ in range(60):
        net.fit(idx, y)
    assert net.score(x=idx, y=y) < s0 * 0.5


def test_clone_independent():
    net = iris_net()
    clone = net.clone()
    it = IrisDataSetIterator(batch_size=150)
    net.fit(it, epochs=2)
    # clone untouched by training the original
    assert not np.allclose(np.asarray(net.params["layer_0"]["W"]),
                           np.asarray(clone.params["layer_0"]["W"]))


def test_mixed_precision_compute_dtype():
    """compute_dtype('bfloat16'): f32 master params/state, bf16 compute,
    training still converges (TPU fast path; no reference equivalent)."""
    import jax
    import jax.numpy as jnp
    conf = (NeuralNetConfiguration.builder().seed(5)
            .updater(Adam(learning_rate=0.05)).compute_dtype("bfloat16")
            .list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4)).build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    y_cls = rng.integers(0, 3, 90)
    x = (rng.standard_normal((90, 4)) * 0.3).astype(np.float32)
    x[:, :3] += np.eye(3, dtype=np.float32)[y_cls] * 2
    y = np.eye(3, dtype=np.float32)[y_cls]
    s0 = net.score(x=x, y=y)
    for _ in range(40):
        net.fit(x, y)
    assert net.score() < 0.3 * s0
    # master params and running state stay float32 across steps
    for leaf in jax.tree_util.tree_leaves(net.params):
        assert leaf.dtype == jnp.float32
    for leaf in jax.tree_util.tree_leaves(net.state):
        if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype, jnp.floating):
            assert leaf.dtype == jnp.float32


def test_cache_mode_remat_numerics_parity():
    """cache_mode('remat') recomputes activations in backward; results must
    be bit-identical to the default path (reference CacheMode semantics:
    a memory policy, never a numerics change)."""
    def make(cache):
        b = NeuralNetConfiguration.builder().seed(4).updater(
            Adam(learning_rate=0.05))
        if cache:
            b = b.cache_mode("remat")
        conf = (b.list()
                .layer(DenseLayer(n_out=16, activation="relu"))
                .layer(DenseLayer(n_out=16, activation="tanh"))
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(4)).build())
        return MultiLayerNetwork(conf).init()

    rng = np.random.default_rng(0)
    x = rng.standard_normal((60, 4)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 60)]
    a, b = make(False), make(True)
    for _ in range(8):
        a.fit(x, y)
        b.fit(x, y)
    assert abs(a.score() - b.score()) < 1e-6
    with pytest.raises(ValueError, match="cache_mode"):
        NeuralNetConfiguration.builder().cache_mode("everything")


# ---- LossFunctionGradientCheck (reference
# gradientcheck/LossFunctionGradientCheck.java: every ILossFunction against
# central differences, targets shaped to each loss's domain) ----------------
@pytest.mark.parametrize("loss,act,target", [
    ("mape", "identity", "positive"),
    ("msle", "relu", "positive"),
    ("mcxent", "softmax", "onehot"),
    ("squared_hinge", "identity", "pm1"),
    ("kl_divergence", "softmax", "simplex"),
    ("poisson", "softplus", "counts"),
    ("cosine_proximity", "identity", "normal"),
    ("wasserstein", "identity", "pm1"),
    ("fmeasure", "sigmoid", "binary"),
])
def test_gradient_check_remaining_losses(loss, act, target):
    out_dim = 3
    conf = (NeuralNetConfiguration.builder()
            .seed(7)
            .updater(Sgd(learning_rate=0.1))
            .dtype("float64")
            .list()
            .layer(DenseLayer(n_out=5, activation="sigmoid"))
            .layer(OutputLayer(n_out=out_dim, activation=act, loss=loss))
            .set_input_type(InputType.feed_forward(3))
            .build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(2)
    x = rng.standard_normal((5, 3))
    if target == "positive":
        y = rng.uniform(0.5, 2.0, (5, out_dim))
    elif target == "onehot":
        y = np.eye(out_dim)[rng.integers(0, out_dim, 5)]
    elif target == "pm1":
        y = rng.choice([-1.0, 1.0], (5, out_dim))
    elif target == "simplex":
        y = rng.uniform(0.1, 1.0, (5, out_dim))
        y /= y.sum(axis=1, keepdims=True)
    elif target == "counts":
        y = rng.integers(0, 5, (5, out_dim)).astype(float)
    elif target == "binary":
        y = rng.integers(0, 2, (5, out_dim)).astype(float)
    else:
        y = rng.standard_normal((5, out_dim))
    assert check_gradients(net, x, y), loss


# ---- NoBiasGradientCheckTests (reference
# gradientcheck/NoBiasGradientCheckTests.java: has_bias=False layers train
# correctly and carry no bias parameter) ------------------------------------
def test_gradient_check_no_bias():
    conf = (NeuralNetConfiguration.builder()
            .seed(9)
            .updater(Sgd(learning_rate=0.1))
            .dtype("float64")
            .list()
            .layer(DenseLayer(n_out=6, activation="tanh", has_bias=False))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent",
                               has_bias=False))
            .set_input_type(InputType.feed_forward(4))
            .build())
    net = MultiLayerNetwork(conf).init()
    for lname, lparams in net.params.items():
        assert "b" not in lparams, (lname, list(lparams))
    rng = np.random.default_rng(3)
    x = rng.standard_normal((6, 4))
    y = np.eye(3)[rng.integers(0, 3, 6)]
    assert check_gradients(net, x, y)


def test_fit_on_device_epoch_scan():
    """fit_on_device: one-dispatch-per-epoch scan training reaches the same
    quality as the per-batch loop and keeps bookkeeping consistent."""
    from deeplearning4j_tpu.nn.conf.updaters import Adam
    conf = (NeuralNetConfiguration.builder()
            .seed(11)
            .updater(Adam(learning_rate=0.05))
            .list()
            .layer(DenseLayer(n_out=12, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    net = MultiLayerNetwork(conf).init()
    from deeplearning4j_tpu.data.mnist import IrisDataSetIterator
    ds = next(iter(IrisDataSetIterator(batch_size=150)))
    x, y = np.asarray(ds.features), np.asarray(ds.labels)
    net.fit_on_device(x, y, batch_size=32, epochs=60)
    # 4 scanned batches + 1 ragged-tail step per epoch
    assert net.iteration == 60 * (150 // 32 + 1)
    assert net.epoch == 60
    ev = net.evaluate(IrisDataSetIterator(batch_size=150))
    assert ev.accuracy() > 0.9, ev.accuracy()
    assert np.isfinite(net.score())


def test_fit_on_device_matches_per_batch_loop_exactly():
    """The scanned epoch is bit-exact with the equivalent per-batch fit."""
    import jax
    def mknet():
        conf = (NeuralNetConfiguration.builder().seed(11)
                .updater(Sgd(learning_rate=0.2)).list()
                .layer(DenseLayer(n_out=12, activation="tanh"))
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(4)).build())
        return MultiLayerNetwork(conf).init()
    from deeplearning4j_tpu.data.mnist import IrisDataSetIterator
    ds = next(iter(IrisDataSetIterator(batch_size=150)))
    x, y = np.asarray(ds.features), np.asarray(ds.labels)
    x, y = x[:128], y[:128]  # divisible: no ragged-tail step
    a, b = mknet(), mknet()
    a.fit_on_device(x, y, batch_size=32, epochs=1, shuffle=False)
    for i in range(4):
        b.fit(x[i*32:(i+1)*32], y[i*32:(i+1)*32])
    for pa, pb in zip(jax.tree_util.tree_leaves(a.params),
                      jax.tree_util.tree_leaves(b.params)):
        np.testing.assert_array_equal(np.asarray(pa), np.asarray(pb))


def test_fit_on_device_fused_multi_epoch():
    """Round 5: listener-free, tail-free multi-epoch fits run as ONE
    dispatch (outer scan over epochs, in-scan permutation).  Bookkeeping
    and learning must match the per-epoch path."""
    from deeplearning4j_tpu.nn.conf.updaters import Adam
    from deeplearning4j_tpu.train.listeners import ScoreIterationListener

    def mknet():
        conf = (NeuralNetConfiguration.builder().seed(11)
                .updater(Adam(learning_rate=0.05)).list()
                .layer(DenseLayer(n_out=12, activation="tanh"))
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(4)).build())
        return MultiLayerNetwork(conf).init()

    from deeplearning4j_tpu.data.mnist import IrisDataSetIterator
    ds = next(iter(IrisDataSetIterator(batch_size=150)))
    x, y = np.asarray(ds.features)[:128], np.asarray(ds.labels)[:128]

    fused = mknet()
    fused.fit_on_device(x, y, batch_size=32, epochs=40)   # fused eligible
    assert ("epochs_scan", 4, 32, 40, True, ((4,),), ((3,),)) \
        in fused._jit_cache
    assert fused.iteration == 160 and fused.epoch == 40
    assert np.isfinite(fused.score())

    loop = mknet()
    loop.set_listeners(ScoreIterationListener(10 ** 6))   # forces per-epoch
    loop.fit_on_device(x, y, batch_size=32, epochs=40)
    assert not any(k[0] == "epochs_scan" for k in loop._jit_cache)
    # equal-quality learning, not bit-equality (key split trees differ)
    assert fused.score() < 0.35 and loop.score() < 0.35


def test_fit_on_device_fused_clears_stale_grad_stats():
    """The fused multi-epoch program discards gradient stats on purpose;
    a following consumer must see "absent" (None), not the previous
    non-fused fit's stale norms (ISSUE 1 satellite)."""
    from deeplearning4j_tpu.nn.conf.updaters import Adam

    conf = (NeuralNetConfiguration.builder().seed(11)
            .updater(Adam(learning_rate=0.05)).list()
            .layer(DenseLayer(n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(4)).build())
    net = MultiLayerNetwork(conf).init()
    from deeplearning4j_tpu.data.mnist import IrisDataSetIterator
    ds = next(iter(IrisDataSetIterator(batch_size=150)))
    x, y = np.asarray(ds.features)[:64], np.asarray(ds.labels)[:64]
    net.fit(x, y)                                  # per-batch path
    assert net._last_grad_stats is not None        # stats recorded
    net.fit_on_device(x, y, batch_size=32, epochs=3)   # fused eligible
    assert any(k[0] == "epochs_scan" for k in net._jit_cache)
    assert net._last_grad_stats is None
