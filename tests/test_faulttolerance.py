"""Fault-tolerance subsystem (ISSUE 5): crash-consistent checkpoint store,
exact fit resume (parity with the uninterrupted run), SIGKILL/SIGTERM
behavior, and worker-failure recovery in the training masters
(deterministic FaultInjector: retry, straggler timeout, elastic
degradation)."""
import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

REPO_ROOT = Path(__file__).resolve().parents[1]

from deeplearning4j_tpu import (InputType, MultiLayerNetwork,  # noqa: E402
                                NeuralNetConfiguration)
from deeplearning4j_tpu.faulttolerance import (  # noqa: E402
    CheckpointConfig, CheckpointManager, CorruptCheckpointError,
    FaultInjector, RetryPolicy)
from deeplearning4j_tpu.faulttolerance.atomic import (  # noqa: E402
    atomic_file, atomic_write_bytes, discard_orphans)
from deeplearning4j_tpu.nn.conf.updaters import Adam, Sgd  # noqa: E402
from deeplearning4j_tpu.nn.layers.feedforward import (  # noqa: E402
    DenseLayer, OutputLayer)
from deeplearning4j_tpu.observability.registry import (  # noqa: E402
    MetricsRegistry, default_registry, set_default_registry)
from deeplearning4j_tpu.parallel.master import (  # noqa: E402
    ParameterAveragingTrainingMaster)


def build_net(seed=42, dropout=None, updater=None):
    dense = dict(n_out=16, activation="relu")
    if dropout:
        dense["dropout"] = dropout
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater(updater or Adam(learning_rate=0.02)).list()
            .layer(DenseLayer(**dense))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4)).build())
    return MultiLayerNetwork(conf).init()


def make_batches(n=10, batch=8, seed=0):
    rng = np.random.default_rng(seed)
    return [(rng.standard_normal((batch, 4), dtype=np.float32),
             np.eye(3, dtype=np.float32)[rng.integers(0, 3, batch)])
            for _ in range(n)]


@pytest.fixture
def live_registry():
    old = default_registry()
    reg = MetricsRegistry(enabled=True)
    set_default_registry(reg)
    yield reg
    set_default_registry(old)


# ------------------------------------------------------------- atomic layer

def test_atomic_write_commits_or_leaves_previous(tmp_path):
    p = str(tmp_path / "state.bin")
    atomic_write_bytes(p, b"v1")
    assert open(p, "rb").read() == b"v1"
    # a failing writer must leave v1 untouched and no temp litter
    with pytest.raises(RuntimeError):
        with atomic_file(p) as tmp:
            with open(tmp, "wb") as f:
                f.write(b"partial")
            raise RuntimeError("crash mid-write")
    assert open(p, "rb").read() == b"v1"
    assert os.listdir(tmp_path) == ["state.bin"]


def test_discard_orphans(tmp_path):
    (tmp_path / ".tmp-ckpt-1-dead").mkdir()
    (tmp_path / ".tmp-ckpt-1-dead" / "f").write_bytes(b"x")
    (tmp_path / "keep.txt").write_text("y")
    assert discard_orphans(str(tmp_path)) == 1
    assert sorted(os.listdir(tmp_path)) == ["keep.txt"]


# --------------------------------------------------------- checkpoint store

def test_manager_roundtrip_restores_everything(tmp_path, live_registry):
    net = build_net(dropout=0.5)
    batches = make_batches(4)
    net.fit(iter(batches))
    mgr = CheckpointManager(str(tmp_path), background=False)
    path = mgr.save(net, cursor={"fit_epoch": 0, "batch_seq": 4},
                    metric=net.get_score())
    assert mgr.latest() == path
    net2, state = mgr.restore()
    assert np.allclose(net2.params_flat(), net.params_flat())
    assert net2.iteration == net.iteration and net2.epoch == net.epoch
    assert np.array_equal(np.asarray(net2._rng), np.asarray(net._rng))
    assert state["cursor"] == {"fit_epoch": 0, "batch_seq": 4}
    # updater state restored leaf-for-leaf
    import jax
    for a, b in zip(jax.tree_util.tree_leaves(net.opt_state),
                    jax.tree_util.tree_leaves(net2.opt_state)):
        assert np.allclose(np.asarray(a), np.asarray(b))
    c = live_registry.get("checkpoint_restore_total")
    assert c is not None and c.labels("ok").value == 1
    h = live_registry.get("checkpoint_write_seconds")
    assert h is not None and h.labels("sync").count == 1
    assert live_registry.get("checkpoint_bytes").labels().sum > 0


def test_retention_keep_last_every_n_and_best(tmp_path):
    net = build_net()
    batches = make_batches(1)
    mgr = CheckpointManager(str(tmp_path), keep_last=2, keep_every_n=5,
                            keep_best=1, background=False)
    # fake a descending metric so "best" is the last save, and step 5
    # survives via keep_every_n
    metrics = {1: 5.0, 2: 4.0, 3: 0.5, 4: 3.0, 5: 2.0, 6: 1.9, 7: 1.8}
    for it in range(1, 8):
        net.fit_batch(batches[0])
        assert net.iteration == it
        mgr.save(net, metric=metrics[it])
    steps = [s for s, _, _ in mgr.checkpoints()]
    # last two (6,7), every-5th (5), best metric 0.5 (3)
    assert steps == [3, 5, 6, 7]


def _sharded_build_net(seed=42):
    """build_net() laid out ZeRO-3 over a dp=4 mesh (every (4,16)/(16,)
    kernel shards with min_shard_size=0)."""
    from deeplearning4j_tpu.parallel import ShardedTrainer, make_mesh
    net = build_net(seed=seed)
    ShardedTrainer(net, make_mesh(dp=4), min_shard_size=0)
    return net


@pytest.mark.skipif("len(__import__('jax').devices()) < 4")
def test_latest_complete_recognizes_sharded_dirs(tmp_path, live_registry):
    """Satellite (ISSUE 13): the promotion poll and its kind filter see
    the sharded layout — and a corrupt SHARD file makes the dir fall
    back exactly like a torn dense checkpoint."""
    mgr = CheckpointManager(str(tmp_path), background=False)
    net = build_net()
    net.fit_batch(make_batches(1)[0])
    mgr.save(net, step=1)                               # dense
    snet = _sharded_build_net()
    p2 = mgr.save_sharded(snet, step=2)                 # sharded
    assert mgr.latest_complete() == (2, p2)
    assert mgr.latest_complete(kind="sharded") == (2, p2)
    step, path = mgr.latest_complete(kind="dense")
    assert step == 1
    assert mgr.latest_complete(after_step=2) is None
    with pytest.raises(ValueError, match="dense"):
        mgr.latest_complete(kind="zipped")
    # corrupt the newest sharded dir's shard payload: the promotion
    # path must skip it and answer the previous complete checkpoint
    shard = next(f for f in os.listdir(p2) if f.endswith(".npz"))
    with open(os.path.join(p2, shard), "r+b") as f:
        f.seek(25)
        f.write(b"\xde\xad\xbe\xef")
    step, _ = mgr.latest_complete()
    assert step == 1
    assert mgr.latest_complete(kind="sharded") is None
    c = live_registry.get("checkpoint_restore_total")
    assert c is not None and c.labels("skipped").value >= 1


@pytest.mark.skipif("len(__import__('jax').devices()) < 4")
def test_retention_recognizes_sharded_dirs(tmp_path):
    """Satellite (ISSUE 13): keep_last / keep_best retention treats
    barrier-written sharded dirs exactly like dense ones — sweeps the
    old, pins the best recorded metric."""
    mgr = CheckpointManager(str(tmp_path), keep_last=2, keep_best=1,
                            background=False)
    snet = _sharded_build_net()
    metrics = {1: 5.0, 2: 0.5, 3: 4.0, 4: 3.0, 5: 2.0}
    for step in range(1, 6):
        mgr.save_sharded(snet, step=step, metric=metrics[step])
    steps = [s for s, _, _ in mgr.checkpoints()]
    # last two (4,5) plus the best metric 0.5 (2) — 1,3 swept
    assert steps == [2, 4, 5]
    for _, path, manifest in mgr.checkpoints():
        assert manifest.get("sharded")
        assert os.path.isfile(os.path.join(path, "topology.json"))


def test_latest_skips_corrupt_and_restore_refuses(tmp_path, live_registry):
    net = build_net()
    net.fit_batch(make_batches(1)[0])
    mgr = CheckpointManager(str(tmp_path), background=False)
    good = mgr.save(net)
    net.fit_batch(make_batches(1)[0])
    bad = mgr.save(net)
    # flip bytes inside the newest checkpoint's params payload
    target = os.path.join(bad, "model.zip")
    blob = bytearray(open(target, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    open(target, "wb").write(bytes(blob))
    assert mgr.latest() == good                    # corrupt one skipped
    with pytest.raises(CorruptCheckpointError) as ei:
        mgr.restore(path=bad)
    assert "model.zip" in str(ei.value)
    c = live_registry.get("checkpoint_restore_total")
    assert c.labels("corrupt").value >= 1
    assert c.labels("skipped").value >= 1


def test_sigkill_mid_checkpoint_leaves_skippable_partial(tmp_path):
    """A saver SIGKILLed mid-stage leaves only a .tmp- orphan: discovery
    ignores it, restore refuses it, sweep removes it — the previous
    committed checkpoint stays the latest."""
    store = str(tmp_path / "store")
    child = subprocess.Popen(
        [sys.executable, "-c", f"""
import os, sys
sys.path.insert(0, {str(REPO_ROOT)!r})
import numpy as np
from tests.test_faulttolerance import build_net, make_batches
from deeplearning4j_tpu.faulttolerance import CheckpointManager
net = build_net()
net.fit_batch(make_batches(1)[0])
mgr = CheckpointManager({store!r}, background=False)
mgr.save(net)                      # one good committed checkpoint
print("SAVED1", flush=True)
net.fit_batch(make_batches(1)[0])
mgr._test_slow_s = 60.0            # stall between staged files
mgr.save(net)                      # parent SIGKILLs us mid-stage
"""],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu",
                 # replace the axon TPU sitecustomize hook: it can
                 # wedge any child jax import (see tests/conftest.py)
                 PYTHONPATH=str(REPO_ROOT)), cwd=str(REPO_ROOT))
    try:
        line = child.stdout.readline()
        assert "SAVED1" in line, line
        deadline = time.time() + 60
        orphan = None
        while orphan is None and time.time() < deadline:
            tmps = [n for n in os.listdir(store) if n.startswith(".tmp-")]
            orphan = os.path.join(store, tmps[0]) if tmps else None
            if orphan is None:
                time.sleep(0.02)
        assert orphan is not None, "staging dir never appeared"
        # give the slow writer a beat to be inside the inter-file sleep
        time.sleep(0.1)
        child.kill()
        child.wait(timeout=30)
    finally:
        if child.poll() is None:
            child.kill()
    mgr = CheckpointManager(store, background=False)
    assert [s for s, _, _ in mgr.checkpoints()] == [1]   # good one only
    assert mgr.latest().endswith("ckpt-00000001")
    with pytest.raises(CorruptCheckpointError):
        mgr.restore(path=orphan)
    assert mgr.sweep_orphans() == 1
    assert not [n for n in os.listdir(store) if n.startswith(".tmp-")]


# --------------------------------------------------------------- fit resume

def test_fit_resume_parity_and_no_recompiles(tmp_path, live_registry):
    """The acceptance parity: a run checkpointed every k steps, 'killed',
    and resumed from a mid checkpoint ends with params matching the
    uninterrupted run — dropout included (RNG restore) — and the resumed
    fit triggers ZERO extra train-step compiles (shared trace cache +
    restored ShapePolicy history)."""
    batches = make_batches(10)

    netA = build_net(dropout=0.5)
    netA.fit(iter(batches), epochs=2)              # uninterrupted

    netB = build_net(dropout=0.5)
    cfg = CheckpointConfig(directory=str(tmp_path),
                           save_every_n_iterations=3, keep_last=10,
                           background=False)
    netB.fit(iter(batches), epochs=2, checkpoint=cfg)
    # checkpointing is an observer: identical params with it on
    assert np.allclose(netA.params_flat(), netB.params_flat())
    mgr = cfg.resolve()
    steps = [s for s, _, _ in mgr.checkpoints()]
    assert steps[0] % 3 == 0 and len(steps) >= 3
    mid = mgr.checkpoints()[1][1]                   # "the kill point"

    def compiles():
        c = live_registry.get("training_compile_total")
        return 0.0 if c is None else sum(
            child.value for _, child in c.samples())

    before = compiles()
    netC = build_net(dropout=0.5)
    netC.fit(iter(batches), epochs=2, resume_from=mid)
    assert compiles() == before                     # counter-verified
    assert np.allclose(netA.params_flat(), netC.params_flat())
    assert netC.iteration == netA.iteration
    assert netC.epoch == netA.epoch


def test_fit_resume_mid_epoch_cursor(tmp_path):
    """Resume lands mid-epoch at the exact batch-seq cursor (not an epoch
    boundary): checkpoint at iteration 4 of a 7-batch epoch."""
    batches = make_batches(7)
    netA = build_net(updater=Sgd(learning_rate=0.05))
    netA.fit(iter(batches), epochs=1)
    netB = build_net(updater=Sgd(learning_rate=0.05))
    cfg = CheckpointConfig(directory=str(tmp_path),
                           save_every_n_iterations=4, background=False)
    netB.fit(iter(batches), epochs=1, checkpoint=cfg)
    ck = cfg.resolve().checkpoints()[0]
    assert ck[0] == 4
    state = json.load(open(os.path.join(ck[1], "training_state.json")))
    assert state["cursor"] == {"fit_epoch": 0, "batch_seq": 4}
    netC = build_net(updater=Sgd(learning_rate=0.05))
    netC.fit(iter(batches), epochs=1, resume_from=ck[1])
    assert np.allclose(netA.params_flat(), netC.params_flat())


def test_fit_on_device_epoch_checkpoint_and_resume(tmp_path):
    rng = np.random.default_rng(5)
    x = rng.standard_normal((32, 4), dtype=np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 32)]

    netA = build_net(seed=9)
    cfgA = CheckpointConfig(directory=str(tmp_path / "a"),
                            save_every_n_epochs=1, keep_last=8,
                            background=False)
    netA.fit_on_device(x, y, batch_size=8, epochs=4, checkpoint=cfgA)

    netB = build_net(seed=9)
    cfgB = CheckpointConfig(directory=str(tmp_path / "b"),
                            save_every_n_epochs=1, keep_last=8,
                            background=False)
    netB.fit_on_device(x, y, batch_size=8, epochs=4, checkpoint=cfgB)
    ckpts = cfgB.resolve().checkpoints()
    assert len(ckpts) == 4
    mid = ckpts[1][1]                               # after epoch 2
    state = json.load(open(os.path.join(mid, "training_state.json")))
    assert state["cursor"]["fit_epoch"] == 2

    netC = build_net(seed=9)
    netC.fit_on_device(x, y, batch_size=8, epochs=4, resume_from=mid)
    assert np.allclose(netA.params_flat(), netC.params_flat())
    assert netC.epoch == netA.epoch == 4


def test_computation_graph_fit_resume_parity(tmp_path):
    from deeplearning4j_tpu.nn.computation_graph import ComputationGraph

    def build_graph():
        conf = (NeuralNetConfiguration.builder().seed(3)
                .updater(Sgd(learning_rate=0.05))
                .graph_builder()
                .add_inputs("in")
                .add_layer("d", DenseLayer(n_out=8, activation="tanh"), "in")
                .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                              loss="mcxent"), "d")
                .set_outputs("out")
                .set_input_types(InputType.feed_forward(4))
                .build())
        return ComputationGraph(conf).init()

    batches = make_batches(6)
    gA = build_graph()
    gA.fit(iter(batches), epochs=2)
    gB = build_graph()
    cfg = CheckpointConfig(directory=str(tmp_path),
                           save_every_n_iterations=4, background=False)
    gB.fit(iter(batches), epochs=2, checkpoint=cfg)
    mid = cfg.resolve().checkpoints()[0][1]
    gC = build_graph()
    gC.fit(iter(batches), epochs=2, resume_from=mid)
    import jax
    for a, b in zip(jax.tree_util.tree_leaves(gA.params),
                    jax.tree_util.tree_leaves(gC.params)):
        assert np.allclose(np.asarray(a), np.asarray(b))


def test_sigterm_triggers_final_save_and_clean_return(tmp_path):
    """save_on_preempt: a SIGTERM mid-fit takes one final synchronous
    checkpoint at the next iteration boundary and fit returns cleanly
    (exit 0) instead of dying — the preemption contract."""
    store = str(tmp_path / "store")
    child = subprocess.Popen(
        [sys.executable, "-c", f"""
import json, os, sys, time
sys.path.insert(0, {str(REPO_ROOT)!r})
import numpy as np
from tests.test_faulttolerance import build_net
from deeplearning4j_tpu.faulttolerance import CheckpointConfig
from deeplearning4j_tpu.train.listeners import TrainingListener

class Ready(TrainingListener):
    def iteration_done(self, model, iteration, epoch):
        if iteration == 1:
            print("READY", flush=True)
        time.sleep(0.01)           # keep the fit alive for the signal

def batches():
    rng = np.random.default_rng(0)
    for _ in range(100000):
        yield (rng.standard_normal((8, 4), dtype=np.float32),
               np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)])

net = build_net()
net.set_listeners(Ready())
cfg = CheckpointConfig(directory={store!r}, save_on_preempt=True,
                       background=False)
net.fit(batches(), epochs=1, checkpoint=cfg)
print(json.dumps({{"iteration": net.iteration}}), flush=True)
"""],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu",
                 # replace the axon TPU sitecustomize hook: it can
                 # wedge any child jax import (see tests/conftest.py)
                 PYTHONPATH=str(REPO_ROOT)), cwd=str(REPO_ROOT))
    try:
        assert "READY" in child.stdout.readline()
        child.send_signal(signal.SIGTERM)
        out, _ = child.communicate(timeout=120)
    finally:
        if child.poll() is None:
            child.kill()
    assert child.returncode == 0, out
    result = json.loads(out.strip().splitlines()[-1])
    assert result["iteration"] >= 1
    mgr = CheckpointManager(store, background=False)
    latest = mgr.latest()
    assert latest is not None
    net2, state = mgr.restore()
    assert net2.iteration == result["iteration"]
    assert state["cursor"]["batch_seq"] >= 1


# ------------------------------------------------- master failure recovery

def master_batches(n=8, seed=1):
    return make_batches(n, seed=seed)


def seq_reference(order, batches, seed=7):
    net = build_net(seed=seed, updater=Sgd(learning_rate=0.05))
    for i in order:
        net.fit_batch(batches[i])
    return net.params_flat()


def test_master_transient_fault_retry_recovers(live_registry):
    """A worker failing once is retried from its round-start snapshot;
    the run's final params equal the fault-free run's."""
    batches = master_batches()
    inj = FaultInjector(seed=0).fail(worker=1, rnd=0, times=1)
    m = ParameterAveragingTrainingMaster(
        2, averaging_frequency=2, max_retries=2, retry_backoff_s=0.001,
        fault_injector=inj)
    netF = build_net(seed=7, updater=Sgd(learning_rate=0.05))
    m.fit(netF, iter(batches))
    m0 = ParameterAveragingTrainingMaster(2, averaging_frequency=2)
    netR = build_net(seed=7, updater=Sgd(learning_rate=0.05))
    m0.fit(netR, iter(batches))
    assert np.allclose(netF.params_flat(), netR.params_flat())
    assert m.retry_counts == {1: 1}
    assert m.lost_workers == set()
    c = live_registry.get("training_worker_retries_total")
    assert c.labels("threads").value == 1
    assert ("fail", 1, 0) in inj.events


def test_master_permanent_failure_elastic_rechunk(live_registry):
    """ISSUE acceptance: one injected permanently-failed worker — fit()
    completes via elastic degradation (round re-chunked over survivors,
    shard redistributed) with deterministically correct params."""
    batches = master_batches()
    inj = FaultInjector(seed=0).fail(worker=1, rnd=0, times=-1)
    m = ParameterAveragingTrainingMaster(
        2, averaging_frequency=2, max_retries=2, retry_backoff_s=0.001,
        fault_injector=inj)
    net = build_net(seed=7, updater=Sgd(learning_rate=0.05))
    m.fit(net, iter(batches))
    assert m.lost_workers == {1}
    assert m.retry_counts == {1: 2}            # full retry budget spent
    # shards: w0=[0,2,4,6], w1=[1,3,5,7], freq=2.  Round 0: w0 runs [0,2];
    # w1's [1,3] re-chunks onto w0; w1's queue [5,7] rides w0's queue.
    # Surviving execution order on w0: 0,2,1,3 | 4,6 | 5,7.
    expect = seq_reference([0, 2, 1, 3, 4, 6, 5, 7], batches)
    assert np.allclose(net.params_flat(), expect)
    c = live_registry.get("training_worker_lost_total")
    assert c.labels("threads").value == 1
    assert live_registry.get(
        "training_worker_retries_total").labels("threads").value == 2


def test_master_straggler_timeout_elastic(live_registry):
    """A worker exceeding the straggler timeout is excluded and its work
    re-chunked; fit completes with the same params as the permanent-loss
    case (the straggler's replica never re-enters aggregation)."""
    batches = master_batches()
    inj = FaultInjector(seed=0).delay(worker=1, rnd=0, seconds=1.5)
    m = ParameterAveragingTrainingMaster(
        2, averaging_frequency=2, max_retries=1, retry_backoff_s=0.001,
        straggler_timeout_s=0.25, fault_injector=inj)
    net = build_net(seed=7, updater=Sgd(learning_rate=0.05))
    t0 = time.monotonic()
    m.fit(net, iter(batches))
    assert m.lost_workers == {1}
    expect = seq_reference([0, 2, 1, 3, 4, 6, 5, 7], batches)
    assert np.allclose(net.params_flat(), expect)
    assert live_registry.get(
        "training_worker_lost_total").labels("threads").value == 1
    assert time.monotonic() - t0 < 30


def test_master_dropped_result_is_retried():
    batches = master_batches()
    inj = FaultInjector(seed=0).drop(worker=0, rnd=1, times=1)
    m = ParameterAveragingTrainingMaster(
        2, averaging_frequency=2, max_retries=2, retry_backoff_s=0.001,
        fault_injector=inj)
    net = build_net(seed=7, updater=Sgd(learning_rate=0.05))
    m.fit(net, iter(batches))
    m0 = ParameterAveragingTrainingMaster(2, averaging_frequency=2)
    netR = build_net(seed=7, updater=Sgd(learning_rate=0.05))
    m0.fit(netR, iter(batches))
    assert np.allclose(net.params_flat(), netR.params_flat())
    assert m.retry_counts == {0: 1}
    assert ("drop", 0, 1) in inj.events


def test_master_rechunk_survivor_transient_fault_recovers():
    """A transient survivor hiccup DURING elastic re-chunk (injector key
    (0, -1)) is retried from a snapshot instead of aborting the fit the
    recovery machinery just saved."""
    batches = master_batches()
    inj = (FaultInjector(seed=0).fail(worker=1, rnd=0, times=-1)
           .fail(worker=0, rnd=-1, times=1))      # re-chunk replay hiccup
    m = ParameterAveragingTrainingMaster(
        2, averaging_frequency=2, max_retries=2, retry_backoff_s=0.001,
        fault_injector=inj)
    net = build_net(seed=7, updater=Sgd(learning_rate=0.05))
    m.fit(net, iter(batches))
    assert m.lost_workers == {1}
    expect = seq_reference([0, 2, 1, 3, 4, 6, 5, 7], batches)
    assert np.allclose(net.params_flat(), expect)


def test_master_straggler_raise_joins_lingering_threads():
    """elastic=False + straggler: the raise path must still join the
    zombie thread before control returns to the caller (its replica is
    the caller's model)."""
    batches = master_batches(4)
    inj = FaultInjector(seed=0).delay(worker=1, rnd=0, seconds=0.8)
    m = ParameterAveragingTrainingMaster(
        2, averaging_frequency=2, max_retries=1, retry_backoff_s=0.001,
        straggler_timeout_s=0.1, fault_injector=inj, elastic=False)
    net = build_net(seed=7, updater=Sgd(learning_rate=0.05))
    with pytest.raises(RuntimeError, match="straggler"):
        m.fit(net, iter(batches))
    assert all(not t.is_alive() for t in m._lingering)


def test_checkpoint_validation_error_leaves_sigterm_handler(tmp_path):
    """A validation raise before training starts must not leak the
    save-on-preempt SIGTERM handler (it is installed only after every
    early raise and uninstalled in the loop's finally)."""
    before = signal.getsignal(signal.SIGTERM)
    conf = (NeuralNetConfiguration.builder().seed(1)
            .updater(Sgd(learning_rate=0.1))
            .optimization_algo("lbfgs").list()
            .layer(DenseLayer(n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4)).build())
    net = MultiLayerNetwork(conf).init()
    cfg = CheckpointConfig(directory=str(tmp_path), save_on_preempt=True,
                           save_every_n_iterations=1, background=False)
    with pytest.raises(ValueError, match="SGD path"):
        net.fit(iter(make_batches(2)), checkpoint=cfg)
    assert signal.getsignal(signal.SIGTERM) is before
    # bad-input raise inside a checkpointed fit also restores the handler
    net2 = build_net()
    with pytest.raises(ValueError, match="fit\\(\\) needs"):
        net2.fit(object(), checkpoint=CheckpointConfig(
            directory=str(tmp_path), save_on_preempt=True,
            save_every_n_iterations=1, background=False))
    assert signal.getsignal(signal.SIGTERM) is before


def test_master_all_workers_lost_raises():
    batches = master_batches(4)
    inj = (FaultInjector(seed=0).fail(worker=0, rnd=0, times=-1)
           .fail(worker=1, rnd=0, times=-1))
    m = ParameterAveragingTrainingMaster(
        2, averaging_frequency=2, max_retries=1, retry_backoff_s=0.001,
        fault_injector=inj)
    net = build_net(seed=7, updater=Sgd(learning_rate=0.05))
    with pytest.raises(RuntimeError, match="all 2 workers lost"):
        m.fit(net, iter(batches))


def test_master_elastic_off_propagates():
    batches = master_batches(4)
    inj = FaultInjector(seed=0).fail(worker=1, rnd=0, times=-1)
    m = ParameterAveragingTrainingMaster(
        2, averaging_frequency=2, max_retries=1, retry_backoff_s=0.001,
        fault_injector=inj, elastic=False)
    net = build_net(seed=7, updater=Sgd(learning_rate=0.05))
    with pytest.raises(Exception, match="injected failure"):
        m.fit(net, iter(batches))


def test_retry_policy_backoff_seeded_and_bounded():
    a = RetryPolicy(max_retries=3, backoff_s=0.1, seed=5)
    b = RetryPolicy(max_retries=3, backoff_s=0.1, seed=5)
    da = [a.backoff(k) for k in range(1, 5)]
    db = [b.backoff(k) for k in range(1, 5)]
    assert da == db                              # seeded => reproducible
    for k, d in enumerate(da, start=1):
        assert 0.05 * 2 ** (k - 1) <= d <= min(0.15 * 2 ** (k - 1), 5.0)
    c = RetryPolicy(backoff_s=10.0, max_backoff_s=1.0, seed=0)
    assert c.backoff(5) == 1.0                   # clamped


# -------------------------------------------------------- listener re-base

def test_checkpoint_listener_no_iteration_zero_save(tmp_path):
    from deeplearning4j_tpu.train.listeners import CheckpointListener
    lst = CheckpointListener(str(tmp_path), save_every_n_iterations=2)
    net = build_net()
    # the old listener saved on iteration 0 (0 % n == 0) — an empty
    # pre-training artifact; the re-based one must not
    lst.iteration_done(net, 0, 0)
    assert lst.saved == []
    net.iteration = 2
    lst.iteration_done(net, 2, 0)
    assert len(lst.saved) == 1
    from deeplearning4j_tpu.utils.model_serializer import restore_model
    back = restore_model(lst.saved[-1])          # dirs restore directly
    assert back.num_params() == net.num_params()
