"""Runtime forensics (ISSUE 10): FlightRecorder ring/dump/checksum
semantics, EventLog size-based rotation, derived JSON p50/p99 exposition,
HealthMonitor streaming detectors (NaN, spike, throughput regression,
padding drift, serving p99/shed-rate — plus the noisy-but-healthy
false-positive posture), and the dump-on-fault triggers: unhandled fit
exceptions, SIGTERM preemption (subprocess), watchdog eviction of a
wedged worker (chaos), ChaosSchedule SIGKILL, serving SLO breaches, and
the manual ``/debug/flightrecorder`` route on the HTTP servers."""
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
from pathlib import Path

import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf.input_type import InputType
from deeplearning4j_tpu.nn.conf.multi_layer import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.updaters import Adam
from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.observability import (EventLog, MetricsRegistry,
                                              bucket_quantile,
                                              configure_event_log,
                                              render_text)
from deeplearning4j_tpu.observability.health import (HealthConfig,
                                                     HealthMonitor,
                                                     set_health_monitor)
from deeplearning4j_tpu.observability.recorder import (DUMP_PREFIX,
                                                       FlightRecorder,
                                                       load_dump,
                                                       set_flight_recorder)

REPO_ROOT = Path(__file__).resolve().parents[1]


def tiny_net(seed=42):
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater(Adam(learning_rate=0.02)).list()
            .layer(DenseLayer(n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4)).build())
    return MultiLayerNetwork(conf).init()


def make_batches(n=10, batch=8, seed=0):
    rng = np.random.default_rng(seed)
    return [(rng.standard_normal((batch, 4), dtype=np.float32),
             np.eye(3, dtype=np.float32)[rng.integers(0, 3, batch)])
            for _ in range(n)]


@pytest.fixture
def recorder(tmp_path):
    """A fresh process-global recorder with a dump directory, restored
    on exit (the module-level default recorder has no directory, so
    auto-triggers in OTHER tests can never litter the disk)."""
    rec = FlightRecorder(capacity=64, directory=str(tmp_path / "frec"),
                         min_dump_interval_s=0.0)
    prev = set_flight_recorder(rec)
    try:
        yield rec
    finally:
        set_flight_recorder(prev)


@pytest.fixture
def monitor():
    """Install a process-global HealthMonitor (isolated registry) and
    restore the previous one on exit."""
    mon = HealthMonitor(HealthConfig(warmup_steps=3),
                        registry=MetricsRegistry(enabled=True))
    prev = set_health_monitor(mon)
    try:
        yield mon
    finally:
        set_health_monitor(prev)


# ------------------------------------------------------- FlightRecorder core

class TestFlightRecorder:
    def test_ring_bounds_and_dropped_accounting(self):
        rec = FlightRecorder(capacity=8)
        for i in range(20):
            rec.record("train", "step", i=i)
        items = rec.channel("train").items()
        assert len(items) == 8
        assert [r["i"] for r in items] == list(range(12, 20))
        assert rec.channel("train").dropped == 12

    def test_dump_roundtrip_checksum_valid(self, tmp_path):
        rec = FlightRecorder(capacity=8, directory=str(tmp_path))
        rec.record("train", "step", i=1, score=0.5)
        rec.record("serving", "dispatch", rows=4)
        rec.record_span({"name": "fit", "duration_s": 0.1})
        reg = MetricsRegistry(enabled=True)
        reg.counter("t_total", "doc").inc(3)
        rec.snapshot_metrics(registry=reg)
        path = rec.dump("unit_test", snapshot=False)
        assert os.path.basename(path).startswith(DUMP_PREFIX)
        payload = load_dump(path)
        assert payload["reason"] == "unit_test"
        assert payload["pid"] == os.getpid()
        assert [r["type"] for r in payload["channels"]["train"]] == ["step"]
        assert payload["channels"]["serving"][0]["rows"] == 4
        assert payload["spans"][0]["name"] == "fit"
        snap = payload["metric_snapshots"][0]["metrics"]
        assert snap["t_total"]["samples"][0]["value"] == 3

    def test_corrupt_artifact_detected(self, tmp_path):
        rec = FlightRecorder(directory=str(tmp_path))
        rec.record("train", "step", i=1)
        path = rec.dump("corrupt_me")
        blob = Path(path).read_text()
        Path(path).write_text(blob.replace('"i": 1', '"i": 2'))
        with pytest.raises(ValueError, match="checksum mismatch"):
            load_dump(path)
        # verify=False still reads it (the "I know, show me anyway" path)
        assert load_dump(path, verify=False)["channels"]["train"]

    def test_maybe_dump_needs_directory_and_rate_limits(self, tmp_path):
        rec = FlightRecorder()           # no directory anywhere
        rec.record("train", "step", i=1)
        assert rec.maybe_dump("no_home") is None
        rec = FlightRecorder(directory=str(tmp_path),
                             min_dump_interval_s=60.0)
        rec.record("train", "step", i=1)
        first = rec.maybe_dump("burst")
        assert first is not None
        assert rec.maybe_dump("burst") is None          # rate-limited
        assert rec.maybe_dump("other_reason") is not None   # per-reason
        assert len(rec.dumps) == 2

    def test_disabled_recorder_is_inert(self, tmp_path):
        rec = FlightRecorder(directory=str(tmp_path), enabled=False)
        rec.record("train", "step", i=1)
        rec.record_span({"name": "s"})
        rec.snapshot_metrics(registry=MetricsRegistry(enabled=True))
        assert rec.dump("nope") is None
        assert len(rec.channel("train")) == 0
        rec.enable()
        rec.record("train", "step", i=2)
        assert len(rec.channel("train")) == 1

    def test_concurrent_record_and_dump(self, tmp_path):
        rec = FlightRecorder(capacity=128, directory=str(tmp_path),
                             min_dump_interval_s=0.0)
        errors = []

        def writer(w):
            try:
                for i in range(500):
                    rec.record(f"chan{w % 2}", "step", w=w, i=i)
            except Exception as e:          # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=writer, args=(w,))
                   for w in range(8)]
        for t in threads:
            t.start()
        paths = [rec.dump(f"mid_flight_{k}") for k in range(3)]
        for t in threads:
            t.join()
        assert errors == []
        for p in paths:                     # every mid-flight dump is valid
            load_dump(p)
        assert len(rec.channel("chan0")) == 128
        r = rec.channel("chan0")
        assert r.dropped == 4 * 500 - 128

    def test_view_shape(self, tmp_path):
        rec = FlightRecorder(capacity=4, directory=str(tmp_path))
        rec.record("train", "step", i=1)
        view = rec.view()
        assert view["enabled"] is True
        assert view["channels"]["train"][0]["i"] == 1
        json.dumps(view)                    # the /debug payload is JSON-able


# ------------------------------------------------------- EventLog rotation

class TestEventLogRotation:
    def test_rotates_and_reads_across_segments_in_order(self, tmp_path):
        path = str(tmp_path / "events.jsonl")
        with EventLog(path, max_bytes=300, max_files=10) as log:
            for i in range(40):
                log.emit("tick", seq=i)
        segments = EventLog.segments(path)
        assert len(segments) > 2
        assert segments[-1] == path          # active file last
        # one continuous stream, oldest first, nothing lost or spliced
        seqs = [r["seq"] for r in EventLog.read(path)]
        assert seqs == list(range(40))
        for seg in segments:                 # every segment is whole JSONL
            for line in Path(seg).read_text().splitlines():
                json.loads(line)

    def test_max_files_drops_oldest(self, tmp_path):
        path = str(tmp_path / "events.jsonl")
        with EventLog(path, max_bytes=120, max_files=2) as log:
            for i in range(60):
                log.emit("tick", seq=i)
        assert set(EventLog.segments(path)) == {path + ".1", path}
        seqs = [r["seq"] for r in EventLog.read(path)]
        assert seqs == sorted(seqs)          # still ordered…
        assert seqs[-1] == 59                # …ends at the newest record
        assert seqs[0] > 0                   # …and the oldest fell off

    def test_no_max_bytes_never_rotates(self, tmp_path):
        path = str(tmp_path / "events.jsonl")
        with EventLog(path) as log:
            for i in range(200):
                log.emit("tick", seq=i)
        assert EventLog.segments(path) == [path]
        assert len(list(EventLog.read(path))) == 200

    def test_configured_log_rotates_and_emit_mirrors_to_recorder(
            self, tmp_path, recorder):
        path = str(tmp_path / "events.jsonl")
        configure_event_log(path, max_bytes=200, max_files=20)
        try:
            from deeplearning4j_tpu.observability import emit_event
            for i in range(30):
                emit_event("tick", seq=i)
        finally:
            configure_event_log(None)
        assert len(EventLog.segments(path)) > 1
        assert [r["seq"] for r in EventLog.read(path)] == list(range(30))
        # every emit also landed in the recorder's crash window
        ring = recorder.channel("events").items()
        assert [r["seq"] for r in ring] == list(range(30))


# ---------------------------------------------------- JSON p50/p99 summaries

class TestDerivedQuantiles:
    def test_bucket_quantile_nearest_rank(self):
        cum = [(0.1, 5), (0.5, 9), (1.0, 9), (float("inf"), 10)]
        assert bucket_quantile(cum, 0.50) == 0.1
        assert bucket_quantile(cum, 0.90) == 0.5
        assert bucket_quantile(cum, 0.99) == 1.0     # +Inf clamps to 1.0
        assert bucket_quantile([], 0.5) is None
        assert bucket_quantile([(0.1, 0), (float("inf"), 0)], 0.5) is None
        with pytest.raises(ValueError):
            bucket_quantile(cum, 1.5)

    def test_json_snapshot_carries_p50_p99(self):
        reg = MetricsRegistry(enabled=True)
        h = reg.histogram("t_req_seconds", "doc", buckets=(0.1, 0.5, 1.0))
        for v in (0.05,) * 5 + (0.4,) * 4 + (2.0,):
            h.observe(v)
        sample = reg.snapshot()["t_req_seconds"]["samples"][0]
        assert sample["p50"] == 0.1
        assert sample["p99"] == 1.0
        assert sample["count"] == 10

    def test_prometheus_text_unchanged(self):
        reg = MetricsRegistry(enabled=True)
        reg.histogram("t_req_seconds", "doc", buckets=(0.1, 1.0)).observe(0.2)
        text = render_text(reg)
        assert "p50" not in text and "p99" not in text
        assert 'le="0.1"' in text


# ----------------------------------------------------------- HealthMonitor

def _mon(**cfg):
    return HealthMonitor(HealthConfig(**cfg),
                         registry=MetricsRegistry(enabled=True),
                         recorder=FlightRecorder())


class TestHealthMonitor:
    def test_nan_loss_detected_sticky_degraded(self):
        mon = _mon()
        dets = mon.observe_step(loss=float("nan"), step=7)
        assert [d.kind for d in dets] == ["nan_loss"]
        assert mon.state() == "degraded"
        assert any("nan_loss" in r for r in mon.reasons())
        # sticky: a NaN does not age out (cooldown only clears spikes)
        mon.config = HealthConfig(degraded_cooldown_s=0.0)
        assert mon.state() == "degraded"
        mon.clear()
        assert mon.state() == "ok" and mon.reasons() == []

    def test_nan_grad_detected(self):
        mon = _mon()
        dets = mon.observe_step(loss=0.5, grad_norm=float("inf"), step=3)
        assert [d.kind for d in dets] == ["nan_grad"]
        assert mon._reg().counter(
            "health_detections_total", "doc",
            ("kind",)).labels("nan_grad").value == 1

    def test_loss_spike_detected_after_warmup_seeded(self):
        mon = _mon(warmup_steps=10, ewma_alpha=0.2, z_threshold=6.0)
        rng = np.random.default_rng(11)
        for step in range(30):
            assert mon.observe_step(
                loss=1.0 + rng.normal(0.0, 0.01), step=step) == []
        dets = mon.observe_step(loss=50.0, step=30)
        assert [d.kind for d in dets] == ["loss_spike"]
        assert "EWMA std devs" in dets[0].reason

    def test_grad_spike_and_dedupe_window_merges(self):
        mon = _mon(warmup_steps=5, ewma_alpha=0.2, z_threshold=6.0,
                   dedupe_s=300.0)
        rng = np.random.default_rng(5)
        for step in range(20):
            mon.observe_step(grad_norm=3.0 + rng.normal(0.0, 0.05),
                             step=step)
        first = mon.observe_step(grad_norm=500.0, step=20)
        assert [d.kind for d in first] == ["grad_spike"]
        # same-kind repeats inside the window merge into ONE incident
        # (NaNs fire unconditionally, so they exercise the merge path)
        assert [d.kind for d in
                mon.observe_step(grad_norm=float("nan"), step=21)] \
            == ["nan_grad"]
        assert mon.observe_step(grad_norm=float("nan"), step=22) == []
        assert mon._by_kind["nan_grad"].count == 2

    def test_throughput_regression(self):
        mon = _mon(ewma_alpha=0.5, throughput_warmup=5,
                   throughput_floor_ratio=0.5)
        for step in range(10):
            assert mon.observe_step(examples_per_sec=1000.0,
                                    step=step) == []
        out = []
        for step in range(10, 20):
            out += mon.observe_step(examples_per_sec=10.0, step=step)
        assert [d.kind for d in out] == ["throughput_regression"]
        assert out[0].value < out[0].threshold

    def test_padding_drift(self):
        mon = _mon(warmup_steps=5, ewma_alpha=0.5, padding_drift=0.25)
        for step in range(5):
            assert mon.observe_step(padding_ratio=1.0, step=step) == []
        out = []
        for step in range(5, 20):
            out += mon.observe_step(padding_ratio=2.0, step=step)
        assert [d.kind for d in out] == ["padding_drift"]

    def test_noisy_but_healthy_stream_no_false_positives(self):
        """The false-positive posture: 300 steps of realistically noisy
        but healthy signals produce ZERO detections under defaults."""
        mon = _mon()
        rng = np.random.default_rng(42)
        for step in range(300):
            dets = mon.observe_step(
                loss=2.0 + rng.normal(0.0, 0.3),
                grad_norm=5.0 + rng.normal(0.0, 1.0),
                examples_per_sec=1000.0 + rng.normal(0.0, 100.0),
                padding_ratio=1.1 + rng.normal(0.0, 0.02),
                step=step)
            assert dets == [], (step, dets)
        assert mon.state() == "ok"

    def test_serving_p99_and_shed_rate_detectors(self):
        mon = _mon(serving_min_samples=4, p99_target_ms=1.0,
                   shed_rate_threshold=0.5)
        out = []
        for _ in range(4):
            out += mon.observe_request(seconds=0.05)
        assert [d.kind for d in out] == ["serving_p99"]
        mon2 = _mon(serving_min_samples=4, shed_rate_threshold=0.5)
        out = []
        for _ in range(4):
            out += mon2.observe_request(shed=True)
        assert [d.kind for d in out] == ["shed_rate"]

    def test_checkpoint_hook_fires_once_per_incident(self):
        saved = []
        mon = _mon(dedupe_s=300.0)
        mon.bind_checkpoint(lambda det: saved.append(det.kind))
        mon.observe_step(loss=float("nan"))
        mon.observe_step(loss=float("nan"))     # merged: no second save
        assert saved == ["nan_loss"]
        assert mon.checkpoint_saves == 1

    def test_stop_training_opt_in(self):
        mon = _mon()                             # default: keep going
        mon.observe_step(loss=float("nan"))
        assert mon.should_stop() is False
        mon2 = _mon(stop_training=True)
        mon2.observe_step(loss=float("nan"))
        assert mon2.should_stop() is True
        mon2.clear()
        assert mon2.should_stop() is False

    def test_detection_lands_in_recorder_and_status(self):
        rec = FlightRecorder()
        mon = HealthMonitor(HealthConfig(),
                            registry=MetricsRegistry(enabled=True),
                            recorder=rec)
        mon.observe_step(loss=float("nan"), step=12)
        ring = rec.channel("health").items()
        assert ring[0]["kind"] == "nan_loss" and ring[0]["step"] == 12
        status = mon.status()
        assert status["state"] == "degraded"
        assert status["detections"][0]["kind"] == "nan_loss"
        json.dumps(status)                  # the /health embed is JSON-able


# --------------------------------------------- fit integration (in-process)

class TestFitIntegration:
    def test_unhandled_fit_exception_dumps_window(self, recorder):
        from deeplearning4j_tpu.train.listeners import TrainingListener

        class Boom(TrainingListener):
            def iteration_done(self, model, iteration, epoch):
                if iteration == 3:
                    raise RuntimeError("boom")

        net = tiny_net()
        net.set_listeners(Boom())
        with pytest.raises(RuntimeError, match="boom"):
            net.fit(iter(make_batches(6)), epochs=1)
        assert len(recorder.dumps) == 1
        payload = load_dump(recorder.dumps[0])
        assert payload["reason"] == "fit_exception"
        train = payload["channels"]["train"]
        assert [r["type"] for r in train[:2]] == ["step", "step"]
        assert train[-1]["type"] == "fit_exception"
        assert "boom" in train[-1]["error"]

    def test_nan_batch_detected_checkpointed_and_stopped(self, tmp_path,
                                                         recorder):
        """ISSUE 10 acceptance: an injected NaN step is caught by the
        monitor, triggers an immediate checkpoint save, and (opt-in)
        stops training cleanly."""
        from deeplearning4j_tpu.faulttolerance import (CheckpointConfig,
                                                       CheckpointManager)
        store = str(tmp_path / "store")
        # warmup far past the run length: the statistical detectors stay
        # unarmed on real (noisy) training signals; the NaN check is
        # unconditional and is the one under test
        mon = HealthMonitor(
            HealthConfig(warmup_steps=100, stop_training=True),
            registry=MetricsRegistry(enabled=True), recorder=recorder)
        prev = set_health_monitor(mon)
        try:
            batches = make_batches(10)
            bad_x = np.full_like(batches[6][0], np.nan)
            batches[6] = (bad_x, batches[6][1])
            net = tiny_net()
            net.fit(iter(batches),
                    epochs=1,
                    checkpoint=CheckpointConfig(directory=store,
                                                background=False))
        finally:
            set_health_monitor(prev)
        assert net.iteration == 7                # halted AT the NaN step
        kinds = {d["kind"] for d in mon.status()["detections"]}
        assert "nan_loss" in kinds
        assert mon.state() == "degraded"
        assert mon.checkpoint_saves >= 1         # the emergency save
        mgr = CheckpointManager(store, background=False)
        assert mgr.latest() is not None
        # the detection is in the recorder's health channel for the dump
        ring = recorder.channel("health").items()
        assert any(r["kind"] == "nan_loss" for r in ring)

    def test_healthy_fit_with_monitor_unaffected(self, recorder):
        mon = HealthMonitor(HealthConfig(),
                            registry=MetricsRegistry(enabled=True))
        prev = set_health_monitor(mon)
        try:
            net = tiny_net()
            net.fit(iter(make_batches(8)), epochs=1)
        finally:
            set_health_monitor(prev)
        assert net.iteration == 8
        assert mon.state() == "ok"
        assert mon.status()["steps_observed"] == 8
        assert recorder.dumps == []              # nothing went wrong
        steps = recorder.channel("train").items()
        assert len(steps) == 8 and steps[-1]["iteration"] == 8


# ------------------------------------------------ serving-side integration

class TestServingIntegration:
    def test_slo_breach_edge_dumps_and_degrades(self, recorder):
        from deeplearning4j_tpu.serving.engine import (AdmissionController,
                                                       SLOConfig)
        mon = _mon()
        ac = AdmissionController(
            slo=SLOConfig(p99_target_ms=1.0, min_samples=4), health=mon)
        for _ in range(4):
            ac.observe(0.050)                    # 50 ms >> 1 ms target
        assert ac.slo_ok() is False
        assert ac.slo_breaches == 1
        assert ac.slo_ok() is False              # steady state: no new edge
        assert ac.slo_breaches == 1
        # the breach edge committed the window to disk…
        assert len(recorder.dumps) == 1
        payload = load_dump(recorder.dumps[0])
        assert payload["reason"] == "slo_breach"
        serving = payload["channels"]["serving"]
        assert serving[-1]["type"] == "slo_breach"
        assert serving[-1]["p99_ms"] > 1.0
        # …and landed in the health monitor
        kinds = {d["kind"] for d in mon.status()["detections"]}
        assert "slo_breach" in kinds or "serving_p99" in kinds
        assert mon.state() == "degraded"

    def test_shed_feeds_health_monitor(self):
        from deeplearning4j_tpu.serving.engine import (AdmissionController,
                                                       ShedError)
        mon = _mon(serving_min_samples=4, shed_rate_threshold=0.5)
        ac = AdmissionController(queue_limit=1, health=mon)
        for _ in range(4):
            with pytest.raises(ShedError):
                ac.admit(1, depth=1)             # queue full: shed
        kinds = {d["kind"] for d in mon.status()["detections"]}
        assert "shed_rate" in kinds

    def test_serving_server_health_embeds_degraded(self, monitor):
        from deeplearning4j_tpu.serving import ServingEngine, ServingServer
        eng = ServingEngine()                    # no model: unready
        srv = ServingServer(engine=eng, warmup=False)
        try:
            monitor.observe_step(loss=float("nan"))
            h = srv.health()
            assert h["status"] == "unready"      # unready wins over degraded
            assert h["health"]["state"] == "degraded"
            assert any("nan_loss" in r for r in h["health"]["reasons"])
        finally:
            eng.shutdown()


# --------------------------------------------- HTTP route + /health flip

class TestHttpRoutes:
    def test_debug_flightrecorder_view_dump_and_degraded_health(
            self, recorder, monitor):
        from deeplearning4j_tpu.parallel import InferenceMode
        from deeplearning4j_tpu.serving import InferenceClient, \
            InferenceServer
        recorder.record("train", "step", i=1)
        server = InferenceServer(
            tiny_net(), inference_mode=InferenceMode.INPLACE).start()
        try:
            client = InferenceClient(f"http://127.0.0.1:{server.port}")
            view = client.get("/debug/flightrecorder")
            assert view["enabled"] is True
            assert view["channels"]["train"][0]["i"] == 1
            res = client.get("/debug/flightrecorder?dump=1")
            assert res["ok"] is True
            assert load_dump(res["path"])["reason"] == "manual"
            # a NaN detection flips /health ok -> degraded with reasons
            assert client.get("/health")["status"] == "ok"
            monitor.observe_step(loss=float("nan"))
            h = client.get("/health")
            assert h["status"] == "degraded"
            assert h["ready"] is True            # degraded still serves
            assert any("nan_loss" in r for r in h["health"]["reasons"])
            monitor.clear()
            assert client.get("/health")["status"] == "ok"
        finally:
            server.stop()

    def test_debug_flightrecorder_503_without_recorder(self):
        from deeplearning4j_tpu.parallel import InferenceMode
        from deeplearning4j_tpu.serving import InferenceClient, \
            InferenceServer
        prev = set_flight_recorder(None)
        server = InferenceServer(
            tiny_net(), inference_mode=InferenceMode.INPLACE).start()
        try:
            client = InferenceClient(f"http://127.0.0.1:{server.port}")
            with pytest.raises(urllib.error.HTTPError) as err:
                client.get("/debug/flightrecorder")
            assert err.value.code == 503
        finally:
            server.stop()
            set_flight_recorder(prev)


# ----------------------------------------------------- dump-on-fault paths

def test_sigterm_preemption_dumps_next_to_checkpoint(tmp_path):
    """ISSUE 10 acceptance: a fit killed by SIGTERM leaves a complete,
    checksum-valid flight-recorder artifact next to the preemption
    checkpoint, containing the final window's train-step records."""
    store = str(tmp_path / "store")
    child = subprocess.Popen(
        [sys.executable, "-c", f"""
import sys, time
sys.path.insert(0, {str(REPO_ROOT)!r})
from tests.test_flightrecorder import make_batches, tiny_net
from deeplearning4j_tpu.faulttolerance import CheckpointConfig
from deeplearning4j_tpu.train.listeners import TrainingListener

class Ready(TrainingListener):
    def iteration_done(self, model, iteration, epoch):
        if iteration == 1:
            print("READY", flush=True)
        time.sleep(0.01)           # keep the fit alive for the signal

def batches():
    while True:
        yield from make_batches(50)

net = tiny_net()
net.set_listeners(Ready())
net.fit(batches(), epochs=1,
        checkpoint=CheckpointConfig(directory={store!r},
                                    save_on_preempt=True,
                                    background=False))
print("CLEAN-RETURN", flush=True)
"""],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu",
                 PYTHONPATH=str(REPO_ROOT)), cwd=str(REPO_ROOT))
    try:
        assert "READY" in child.stdout.readline()
        child.send_signal(signal.SIGTERM)
        out, _ = child.communicate(timeout=120)
    finally:
        if child.poll() is None:
            child.kill()
    assert child.returncode == 0, out
    assert "CLEAN-RETURN" in out
    dumps = [f for f in os.listdir(store)
             if f.startswith(DUMP_PREFIX + "preempt")]
    assert len(dumps) == 1, os.listdir(store)
    payload = load_dump(os.path.join(store, dumps[0]))   # checksum-valid
    assert payload["reason"] == "preempt"
    train = payload["channels"]["train"]
    steps = [r for r in train if r["type"] == "step"]
    assert steps and steps[-1]["iteration"] >= 1
    assert train[-1]["type"] == "preempted"
    assert train[-1]["saved"]            # the preemption checkpoint path
    # the checkpoint the dump sits next to is itself restorable
    from deeplearning4j_tpu.faulttolerance import CheckpointManager
    assert CheckpointManager(store, background=False).latest() is not None


def test_chaos_sigkill_triggers_fault_dump(recorder):
    """A ChaosSchedule SIGKILL (the chaos-harness fault) lands on the
    recorder's cluster channel and commits a dump from the surviving
    (killing) side."""
    from deeplearning4j_tpu.faulttolerance.faults import ChaosSchedule
    victim = subprocess.Popen([sys.executable, "-c",
                               "import time; time.sleep(60)"])
    sched = ChaosSchedule(seed=1).kill_process(0, 0.2)
    sched.start(lambda: {0: victim.pid} if victim.poll() is None else {})
    try:
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and \
                not any(e[0] == "kill" for e in sched.events):
            time.sleep(0.05)
    finally:
        sched.stop()
        if victim.poll() is None:
            victim.kill()
        victim.wait()
    assert any(e[0] == "kill" for e in sched.events), sched.events
    assert len(recorder.dumps) == 1
    payload = load_dump(recorder.dumps[0])
    assert payload["reason"] == "chaos_fault"
    cluster = payload["channels"]["cluster"]
    assert any(r["type"] == "chaos_kill" and r["pid"] == victim.pid
               for r in cluster)


@pytest.mark.chaos
def test_watchdog_eviction_dumps_evicted_workers_channel(tmp_path,
                                                         recorder):
    """ISSUE 10 acceptance: when the master_mp watchdog kills a wedged
    worker, the surviving coordinator commits a flight-recorder dump
    into the job directory whose cluster channel carries the evicted
    worker's heartbeat trail and the eviction record itself."""
    from deeplearning4j_tpu.parallel.master_mp import MultiprocessMaster
    rng = np.random.default_rng(0)
    batches = []
    for _ in range(8):
        x = rng.standard_normal((16, 4)).astype(np.float32)
        yc = (x[:, 0] > 0).astype(int) + (x[:, 1] > 0).astype(int)
        batches.append((x, np.eye(3, dtype=np.float32)[yc]))
    model = tiny_net(seed=7)
    master = MultiprocessMaster(
        num_workers=2, mode="averaging", averaging_frequency=2,
        worker_env={"JAX_PLATFORMS": "cpu"}, retry_backoff_s=0.05,
        straggler_timeout_s=8.0,
        fault_injection={"hang_after_batches": {"1": 1}})
    jobdir = str(tmp_path / "job")
    master.fit(model, iter(batches), jobdir=jobdir)
    assert 1 in master.evicted_workers
    # the fault hook rides the worker spec, so a respawned incarnation
    # can wedge and be evicted again — at least one dump, maybe two
    dumps = sorted(f for f in os.listdir(jobdir)
                   if f.startswith(DUMP_PREFIX + "watchdog_eviction"))
    assert dumps, os.listdir(jobdir)
    payload = load_dump(os.path.join(jobdir, dumps[0]))  # checksum-valid
    assert payload["reason"] == "watchdog_eviction"
    cluster = payload["channels"]["cluster"]
    evictions = [r for r in cluster if r["type"] == "watchdog_eviction"]
    assert evictions and evictions[0]["worker"] == 1
    assert evictions[0]["stalled_s"] >= 8.0
    # the evicted worker's own heartbeat trail is IN the artifact
    assert any(r["type"] == "heartbeat" and r["worker"] == 1
               for r in cluster)
