"""graftaudit: the IR-level audit gate (ISSUE 14).

Three layers, mirroring test_lint.py's structure for graftlint:

* **rule units** — each AX rule has a synthetic program that MUST fire
  and one that MUST stay silent (fast: jaxpr phase only, no XLA
  compiles except where the rule is about compiled HLO, which is fed a
  hand-written HLO text).
* **the canonical gate** — the canonical program set (dense / ZeRO-3
  dp=2,4 / bf16 / f16 train steps, serve, prefill, decode), built
  through the REAL production entry points, audits clean against the
  ratcheted EMPTY baseline (justified manifest suppressions allowed,
  none stale).
* **the golden collective signature** — the dp=2 and dp=4 ZeRO-3
  train-step censuses are pinned EXACTLY, so a GSPMD layout regression
  (a dense all-reduce where the sharding implies scatter/gather) fails
  tier-1 instead of a profile review.
"""
import json
import sys
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp

REPO_ROOT = Path(__file__).resolve().parents[1]
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))

from tools.graftaudit import (AUDIT_RULES, AUDIT_RULE_DOCS,  # noqa: E402
                              AuditConfig, AuditProgram, ProgramIR,
                              Suppression, audit_programs, build_card,
                              card_filename, load_card)
from tools.graftaudit.canonical import (CANONICAL_CONFIG,  # noqa: E402
                                        build_canonical)
from tools.graftaudit.cards import STABLE_FIELDS  # noqa: E402
from tools.graftaudit.hlo import (census_from_ops,  # noqa: E402
                                  parse_collectives)
from tools.graftlint.core import Baseline  # noqa: E402

from deeplearning4j_tpu.nn.compile_cache import (  # noqa: E402
    InstrumentedJit, audit_capture_mode, set_audit_capture)

BASELINE = REPO_ROOT / "tools" / "graftaudit" / "baseline.json"
CARDS_DIR = REPO_ROOT / "tools" / "graftaudit" / "cards"

#: jaxpr phase only — rule units never pay an XLA compile
FAST = AuditConfig(compile="never", min_donate_bytes=256)


def prog(fun, *args, name="train_step", donate=(), **kw) -> AuditProgram:
    """Synthetic audit program: jit `fun` standalone (no shared-cache
    pollution), run it once so the spec records, wrap for the rules."""
    entry = InstrumentedJit(fun, name=name, donate_argnums=donate)
    entry(*args)
    specs = entry.audit_specs()
    assert specs, "trace-time capture should have recorded the spec"
    return AuditProgram(name=name, entry=entry, spec=specs[-1], **kw)


def run_rule(code, p, config=FAST):
    from tools.graftaudit import analyze_program
    return AUDIT_RULES[code](analyze_program(p, config))


# ------------------------------------------------------------ spec capture
class TestSpecCapture:
    def test_trace_mode_records_once_per_variant(self):
        entry = InstrumentedJit(lambda x: x * 2, name="t")
        entry(jnp.ones((4,)))
        entry(jnp.ones((4,)))            # steady call: no new spec
        assert len(entry.audit_specs()) == 1
        entry(jnp.ones((8,)))            # new shape: new trace, new spec
        assert len(entry.audit_specs()) == 2

    def test_off_mode_records_nothing(self):
        prev = audit_capture_mode()
        set_audit_capture("off")
        try:
            entry = InstrumentedJit(lambda x: x + 1, name="t")
            entry(jnp.ones((4,)))
            assert entry.audit_specs() == []
        finally:
            set_audit_capture(prev)

    def test_unknown_mode_rejected(self):
        with pytest.raises(ValueError):
            set_audit_capture("sometimes")

    def test_python_scalars_replayed_verbatim(self):
        entry = InstrumentedJit(lambda x, n: x * n, name="t")
        entry(jnp.ones((4,)), 3)
        (args, _kwargs) = entry.audit_specs()[0]
        assert args[1] == 3 and isinstance(args[1], int)
        # and the replayed jaxpr matches the production trace
        closed = entry.audit_jaxpr(entry.audit_specs()[0])
        assert closed.jaxpr.eqns

    def test_audit_lower_does_not_tick_compile_counters(self):
        from deeplearning4j_tpu.observability.registry import \
            default_registry
        entry = InstrumentedJit(lambda x: x * 3, name="audit_probe_fn")
        entry(jnp.ones((4,)))
        c = default_registry().get("training_compile_total")
        before = c.labels("audit_probe_fn").value
        entry.audit_lower(entry.audit_specs()[0]).compile()
        assert c.labels("audit_probe_fn").value == before


# --------------------------------------------------------------- rule units
class TestAX001:
    def test_escaping_f64_promotion_fires(self):
        if not jax.config.jax_enable_x64:
            pytest.skip("needs x64 for a dtype-defaulted f64")

        def fn(x):
            return jnp.sum(x) + jnp.zeros(())   # f64 joins an f32 loss

        fs = run_rule("AX001", prog(fn, jnp.ones((4,), jnp.float32)))
        assert len(fs) == 1 and fs[0].rule == "AX001"
        assert "float64" in fs[0].message

    def test_contained_scalar_f64_stays_silent(self):
        if not jax.config.jax_enable_x64:
            pytest.skip("needs x64")

        def fn(x, n):
            # optax-style weak bias correction: f64 scalar consumed
            # straight back into f32 math — byte-free, no finding
            corr = 1.0 - 0.9 ** n.astype(jnp.float64)
            return x / corr.astype(jnp.float32)

        fs = run_rule("AX001", prog(fn, jnp.ones((4,), jnp.float32),
                                    jnp.asarray(3, jnp.int32)))
        assert fs == []

    def test_escape_elsewhere_does_not_drag_in_contained_scalars(self):
        """Per-origin judgement: one real escaping promotion plus
        contained bias-correction math must report ONLY the escaping
        origin (the program-global-boolean design would flag both)."""
        if not jax.config.jax_enable_x64:
            pytest.skip("needs x64")

        def fn(x, n):
            corr = 1.0 - 0.9 ** n.astype(jnp.float64)   # contained
            y = x / corr.astype(jnp.float32)
            return jnp.sum(y) + jnp.zeros(())           # escaping

        fs = run_rule("AX001", prog(fn, jnp.ones((4,), jnp.float32),
                                    jnp.asarray(3, jnp.int32)))
        assert len(fs) == 1
        assert "1 `convert_element_type`" in fs[0].message

    def test_f64_inputs_mean_f64_is_wanted(self):
        if not jax.config.jax_enable_x64:
            pytest.skip("needs x64")

        def fn(x):
            return jnp.sum(x) * 2.0

        fs = run_rule("AX001", prog(fn, jnp.ones((4,), jnp.float64)))
        assert fs == []

    def test_non_steady_program_out_of_scope(self):
        if not jax.config.jax_enable_x64:
            pytest.skip("needs x64")

        def fn(x):
            return jnp.sum(x) + jnp.zeros(())

        fs = run_rule("AX001", prog(fn, jnp.ones((4,), jnp.float32),
                                    steady=False))
        assert fs == []


class TestAX002:
    def test_f32_dot_inside_bf16_program_fires(self):
        def fn(a, b):
            lo = jnp.dot(a.astype(jnp.bfloat16), b.astype(jnp.bfloat16))
            hi = jnp.dot(a, b)                   # policy leak
            return lo.astype(jnp.float32) + hi

        fs = run_rule("AX002", prog(fn, jnp.ones((4, 4), jnp.float32),
                                    jnp.ones((4, 4), jnp.float32),
                                    policy="bfloat16"))
        assert any("f32 `dot_general`" in f.message for f in fs)

    def test_all_bf16_dots_stay_silent(self):
        def fn(a, b):
            lo = jnp.dot(a.astype(jnp.bfloat16), b.astype(jnp.bfloat16))
            return lo.astype(jnp.float32)

        fs = run_rule("AX002", prog(fn, jnp.ones((4, 4), jnp.float32),
                                    jnp.ones((4, 4), jnp.float32),
                                    policy="bfloat16"))
        assert [f for f in fs if "f32 `dot" in f.message] == []

    def test_cast_uncast_churn_fires(self):
        def fn(x):
            return x.astype(jnp.bfloat16).astype(jnp.float32) + 1.0

        fs = run_rule("AX002", prog(fn, jnp.ones((8,), jnp.float32)))
        assert any("churn" in f.message and "bfloat16" in f.message
                   for f in fs)

    def test_one_way_cast_is_not_churn(self):
        # NB `jnp.sum(x.astype(bf16))` would NOT be a valid negative
        # here: jnp.sum upcasts sub-32-bit floats back to f32 for the
        # accumulation — a genuine round trip the rule rightly flags
        def fn(x):
            return x.astype(jnp.bfloat16) * 2

        fs = run_rule("AX002", prog(fn, jnp.ones((8,), jnp.float32)))
        assert [f for f in fs if "churn" in f.message] == []


class TestAX003:
    def _ir(self, ops, zero3=True, param_bytes=4096,
            name="train_step[zero3,dp=2]"):
        return ProgramIR(
            name=name, kind="train_step", steady=True, policy=None,
            zero3=zero3, config=FAST, jaxpr=None, spec=None,
            donate=(0, 1, 2), arg_bytes=[param_bytes],
            param_bytes=param_bytes, input_dtypes=["float32"],
            census=census_from_ops(ops), census_source="hlo",
            collective_ops=ops)

    def test_dense_gradient_all_reduce_fires(self):
        hlo = """
  %ar = f32[1024]{0} all-reduce(f32[1024]{0} %grads), replica_groups={}
"""
        ops = parse_collectives(hlo)
        fs = AUDIT_RULES["AX003"](self._ir(ops))       # 4096B >= 50%
        assert len(fs) == 1 and "reduce-scatter" in fs[0].message

    def test_small_all_reduce_stays_silent(self):
        hlo = "  %ar = f32[4]{0} all-reduce(f32[4]{0} %gnorm)\n"
        fs = AUDIT_RULES["AX003"](self._ir(parse_collectives(hlo)))
        assert fs == []

    def test_non_zero3_program_out_of_scope(self):
        hlo = "  %ar = f32[1024]{0} all-reduce(f32[1024]{0} %g)\n"
        fs = AUDIT_RULES["AX003"](self._ir(parse_collectives(hlo),
                                           zero3=False))
        assert fs == []

    def test_duplicate_all_gather_fires(self):
        hlo = """
  %ag1 = f32[64,32]{1,0} all-gather(f32[16,32]{1,0} %param.3)
  %ag2 = f32[64,32]{1,0} all-gather(f32[16,32]{1,0} %param.3)
  %ag3 = f32[64,32]{1,0} all-gather(f32[16,32]{1,0} %param.9)
"""
        fs = AUDIT_RULES["AX003"](self._ir(parse_collectives(hlo)))
        assert len(fs) == 1 and "all-gathered 2x" in fs[0].message

    def test_tiny_duplicate_index_gathers_stay_silent(self):
        """The dup-gather arm targets duplicated PARAM gathers; XLA
        re-gathering a 32-byte id block inside separate fusions (the
        sparse-embedding coalesce) is below dup_gather_bytes and must
        not fire."""
        hlo = """
  %ag1 = s32[8]{0} all-gather(s32[4]{0} %ids.1)
  %ag2 = s32[8]{0} all-gather(s32[4]{0} %ids.1)
  %ag3 = s32[8]{0} all-gather(s32[4]{0} %ids.1)
"""
        fs = AUDIT_RULES["AX003"](self._ir(parse_collectives(hlo)))
        assert fs == []

    def test_parse_census_counts_and_bytes(self):
        hlo = """
  %ag = f32[64,32]{1,0} all-gather(f32[16,32]{1,0} %p0)
  %rs = bf16[8,32]{1,0} reduce-scatter(bf16[32,32]{1,0} %g0), dims={0}
  %done = f32[4]{0} all-reduce-done(f32[4]{0} %start)
"""
        census = census_from_ops(parse_collectives(hlo))
        assert census == {
            "all-gather": {"count": 1, "bytes": 64 * 32 * 4},
            "reduce-scatter": {"count": 1, "bytes": 8 * 32 * 2},
        }

    def test_parse_async_start_counts_result_not_operand_alias(self):
        """A `-start` LHS is a state tuple aliasing the operand (and
        collective-permute adds u32[] context slots): only the true
        result bytes may count, or every async census double-bills."""
        hlo = """
  %ags = (f32[16,32]{1,0}, f32[64,32]{1,0}) all-gather-start(f32[16,32]{1,0} %p0)
  %agd = f32[64,32]{1,0} all-gather-done(f32[64,32]{1,0} %ags)
  %cps = (f32[8,8]{1,0}, f32[8,8]{1,0}, u32[], u32[]) collective-permute-start(f32[8,8]{1,0} %x)
"""
        census = census_from_ops(parse_collectives(hlo))
        assert census == {
            "all-gather": {"count": 1, "bytes": 64 * 32 * 4},
            "collective-permute": {"count": 1, "bytes": 8 * 8 * 4},
        }


class TestAX004:
    def test_debug_print_in_steady_program_fires(self):
        def fn(x):
            jax.debug.print("loss={l}", l=jnp.sum(x))
            return x * 2

        fs = run_rule("AX004", prog(fn, jnp.ones((4,))))
        assert len(fs) == 1 and "debug_callback" in fs[0].message

    def test_pure_callback_fires(self):
        def fn(x):
            y = jax.pure_callback(
                lambda v: np.asarray(v) * 2,
                jax.ShapeDtypeStruct(x.shape, x.dtype), x)
            return y + 1

        fs = run_rule("AX004", prog(fn, jnp.ones((4,), jnp.float32)))
        assert len(fs) == 1 and "pure_callback" in fs[0].message

    def test_clean_program_silent_and_setup_out_of_scope(self):
        def clean(x):
            return x * 2

        assert run_rule("AX004", prog(clean, jnp.ones((4,)))) == []

        def dbg(x):
            jax.debug.print("x={x}", x=x)
            return x

        assert run_rule("AX004", prog(dbg, jnp.ones((4,)),
                                      steady=False)) == []


class TestAX005:
    def test_large_dead_arg_not_donated_fires(self):
        def fn(params, state, x):
            return x @ params + 0 * jnp.sum(state)

        p = prog(fn, jnp.ones((64, 64)), jnp.ones((2,)),
                 jnp.ones((8, 64)), name="serve")
        fs = run_rule("AX005", p)
        assert len(fs) == 1
        assert "arg 2" in fs[0].message

    def test_donated_dead_arg_silent(self):
        def fn(params, state, x):
            return x @ params + 0 * jnp.sum(state)

        p = prog(fn, jnp.ones((64, 64)), jnp.ones((2,)),
                 jnp.ones((8, 64)), name="serve", donate=(2,))
        assert run_rule("AX005", p) == []

    def test_below_threshold_and_unknown_kind_silent(self):
        def fn(params, state, x):
            return x @ params + 0 * jnp.sum(state)

        tiny = AuditConfig(compile="never", min_donate_bytes=1 << 30)
        p = prog(fn, jnp.ones((64, 64)), jnp.ones((2,)),
                 jnp.ones((8, 64)), name="serve")
        assert run_rule("AX005", p, tiny) == []
        q = prog(fn, jnp.ones((64, 64)), jnp.ones((2,)),
                 jnp.ones((8, 64)), name="output")
        assert run_rule("AX005", q) == []


class TestAX006:
    def test_oversized_materialized_broadcast_fires(self):
        cfg = AuditConfig(compile="never", broadcast_bytes=1 << 12,
                          broadcast_ratio=4)

        def fn(x):
            big = jnp.broadcast_to(x[:, None], (256, 256))
            return big * 2.0      # the broadcast must survive into math

        fs = run_rule("AX006", prog(fn, jnp.ones((256,), jnp.float32)),
                      cfg)
        assert len(fs) == 1 and "broadcast_in_dim" in fs[0].message

    def test_small_broadcast_silent(self):
        cfg = AuditConfig(compile="never", broadcast_bytes=1 << 20)

        def fn(x):
            return jnp.broadcast_to(x[:, None], (16, 16)) * 2.0

        assert run_rule("AX006", prog(fn, jnp.ones((16,))), cfg) == []


# ------------------------------------------------- suppressions + plumbing
class TestSuppressions:
    def test_reason_is_mandatory(self):
        with pytest.raises(ValueError):
            Suppression("serve", "AX005", "")

    def test_unused_suppression_is_stale(self):
        def fn(x):
            return x * 2

        p = prog(fn, jnp.ones((4,)))
        res = audit_programs(
            [p], [Suppression(p.name, "AX004", "no such finding")], FAST)
        assert res.findings == []
        assert res.stale_suppressions == [f"{p.name}::AX004"]

    def test_suppression_absorbs_and_counts(self):
        def fn(x):
            jax.debug.print("x={x}", x=x)
            return x

        p = prog(fn, jnp.ones((4,)))
        res = audit_programs(
            [p], [Suppression(p.name, "AX004",
                              "unit fixture: deliberate callback")], FAST)
        assert res.findings == []
        assert res.suppressed == {f"{p.name}::AX004": 1}
        assert res.stale_suppressions == []

    def test_duplicate_program_names_rejected(self):
        def fn(x):
            return x

        p1, p2 = prog(fn, jnp.ones((2,))), prog(fn, jnp.ones((3,)))
        p2.name = p1.name
        with pytest.raises(ValueError):
            audit_programs([p1, p2], [], FAST)


def test_rule_catalog_is_complete():
    assert sorted(AUDIT_RULES) == \
        [f"AX00{i}" for i in range(1, 10)] + ["AX010"]
    assert sorted(AUDIT_RULE_DOCS) == sorted(AUDIT_RULES)


# -------------------------------------------------------- the canonical gate
@pytest.fixture(scope="module")
def canonical_audit():
    """Build + audit the full canonical program set ONCE for the gate
    tests (a handful of tiny fits/serves/generates plus their audit
    compiles — the expensive part of this module)."""
    cs = build_canonical()
    assert cs.skipped == {}, cs.skipped   # the tier-1 rig builds ALL
    return audit_programs(cs.programs, cs.suppressions,
                          CANONICAL_CONFIG), cs.programs


def test_canonical_set_audits_clean_modulo_empty_baseline(canonical_audit):
    """THE gate: every steady-state program class the framework ships
    audits clean.  The baseline is ratcheted EMPTY — new IR-level
    findings must be fixed or suppressed IN THE MANIFEST with a
    justification, never silently absorbed."""
    result, programs = canonical_audit
    # 9 since the dense SlotRing removal: paged_prefill/paged_decode are
    # the only generation pair
    assert len(programs) >= 9, [p.name for p in programs]
    bl = Baseline.load(str(BASELINE))
    assert bl.allowances == {}, "graftaudit baseline must stay empty"
    kept, stale = bl.apply(result.findings)
    assert kept == [], "\n".join(f.format() for f in kept)
    assert result.stale_suppressions == []
    # the manifest's CPU donation pragmas actually absorbed something
    # (AX005 threshold-heuristic pragmas for every request path — the
    # paged pair is the only generation pair since the dense SlotRing
    # removal — plus the exact-solver AX007 twins where the lifetime
    # solver proves the threaded pool donatable — serve has no AX007
    # pragma: its batch output aliases nothing, so the solver is
    # rightly silent)
    if jax.default_backend() == "cpu":
        assert set(result.suppressed) == {
            "serve::AX005",
            "paged_prefill::AX005", "paged_decode::AX005",
            "paged_prefill::AX007", "paged_decode::AX007"}


def test_golden_zero3_collective_signature(canonical_audit):
    """The golden collective signature (ISSUE 14 satellite): the dp=2
    and dp=4 ZeRO-3 train steps' collective censuses, pinned EXACTLY.

    What the numbers mean on this backend: GSPMD turns the gradient
    reduction into scatter-reduce form — XLA:CPU lowers the
    reduce-scatter of the three kernel grads as `all-to-all` + local
    add (bytes halve from dp=2 to dp=4: each process ships 1/dp of the
    1280-byte dp=2 volume) — while the 6 `all-gather`s are the forward/
    backward param gathers (4512 bytes: kernels + biases in f32) and
    the 11 small `all-reduce`s (1092 bytes) are scalar loss/gnorm/
    bias-correction reductions.  A REGRESSION looks like: all-to-all
    (or reduce-scatter) disappearing while all-reduce bytes jump to
    ~param scale — the dense-gradient pattern AX003 flags — or the
    all-gather count doubling (a lost CSE gathering a leaf twice).
    Deterministic across processes and x64 modes (verified while
    pinning)."""
    result, _ = canonical_audit
    by_name = {ir.name: ir for ir in result.irs}
    if "train_step[zero3,dp=2]" not in by_name:
        pytest.skip("needs >= 4 virtual devices for the sharded programs")
    assert by_name["train_step[zero3,dp=2]"].census == {
        "all-gather": {"count": 6, "bytes": 4512},
        "all-reduce": {"count": 11, "bytes": 1092},
        "all-to-all": {"count": 3, "bytes": 1280},
    }
    assert by_name["train_step[zero3,dp=4]"].census == {
        "all-gather": {"count": 6, "bytes": 4512},
        "all-reduce": {"count": 11, "bytes": 1092},
        "all-to-all": {"count": 3, "bytes": 640},
    }
    for name in ("train_step[zero3,dp=2]", "train_step[zero3,dp=4]"):
        assert by_name[name].census_source == "hlo"
        assert by_name[name].zero3


def test_embedding_zero3_no_dense_table_exchange(canonical_audit):
    """ISSUE 15 acceptance pin: the sparse-embedding ZeRO-3 train step
    (``sparse_grad=True`` table row-sharded over dp=2) exchanges
    densified touched-row index+value blocks — NO collective in its
    partitioned HLO may carry O(vocab·dim) bytes.  A regression looks
    like: the touched-row gather degrading to an all-gather of the
    full ``[vocab, dim]`` table, or the backward segment-sum degrading
    to a dense-gradient all-reduce (AX003's subject) — either puts a
    table-sized result in the census, and this pin (plus the committed
    card diff) fails tier-1 instead of a profile review.  The zero
    steady-state recompile half of the acceptance line is pinned
    counter-side in tests/test_sparse_embedding.py."""
    from tools.graftaudit.canonical import EMBED_DIM, EMBED_VOCAB

    result, _ = canonical_audit
    by_name = {ir.name: ir for ir in result.irs}
    if "train_step[embedding_zero3]" not in by_name:
        pytest.skip("needs >= 2 virtual devices for the sharded program")
    prog = by_name["train_step[embedding_zero3]"]
    assert prog.zero3 and prog.census_source == "hlo"
    table_bytes = EMBED_VOCAB * EMBED_DIM * 4
    worst = max((c.result_bytes for c in prog.collective_ops), default=0)
    assert 0 < worst * 8 <= table_bytes, \
        f"a {worst}-byte collective is within 8x of the " \
        f"{table_bytes}-byte table — the densified exchange regressed"
    # the COMMITTED card carries the same pin: even the aggregate
    # census (all collectives summed) stays under one dense table
    card = load_card(str(CARDS_DIR / card_filename(prog.name)))
    total = sum(v["bytes"] for v in card["collectives"].values())
    assert 0 < total < table_bytes


def test_committed_cards_match_fresh_audit(canonical_audit):
    """Every canonical program has a committed card whose environment-
    stable fields (collective census, donation map, kind/policy flags)
    match a fresh audit — the PR-over-PR IR diff artifact can't drift
    from reality.  And no ORPHANS: every committed card must name a
    current canonical program (a renamed/removed program's card would
    keep documenting a dead program — `--write-cards` prunes them)."""
    from tools.graftaudit.canonical import CANONICAL_PROGRAM_NAMES

    result, _ = canonical_audit
    for ir_prog in result.irs:
        path = CARDS_DIR / card_filename(ir_prog.name)
        assert path.exists(), f"missing committed card {path}"
        committed = load_card(str(path))
        fresh = build_card(ir_prog)
        for field in STABLE_FIELDS:
            assert committed[field] == fresh[field], \
                f"{ir_prog.name}: card field '{field}' drifted — " \
                "regenerate with `python -m tools.graftaudit --write-cards`"
    legal = {card_filename(n) for n in CANONICAL_PROGRAM_NAMES}
    on_disk = {p.name for p in CARDS_DIR.glob("*.json")}
    assert on_disk <= legal, f"orphan card(s): {sorted(on_disk - legal)}"


def test_write_cards_prunes_orphans_but_keeps_skipped(canonical_audit,
                                                      tmp_path):
    from tools.graftaudit.cards import write_cards

    result, _ = canonical_audit
    orphan = tmp_path / "dead_program.json"
    orphan.write_text("{}")
    skipped = tmp_path / card_filename("train_step[zero3,dp=2]")
    skipped.write_text("{}")
    write_cards(result.irs[:1], str(tmp_path))          # subset: no prune
    assert orphan.exists()
    # full-set prune: the orphan dies, but a program this HOST merely
    # couldn't build (keep=) is live — its committed card must survive
    write_cards(result.irs[:1], str(tmp_path), prune=True,
                keep={skipped.name})
    assert not orphan.exists()
    assert skipped.exists()
    assert (tmp_path / card_filename(result.irs[0].name)).exists()


def test_failed_compile_degrades_loudly_not_silently():
    """A broken HLO phase must never 'audit clean' with an empty
    census: census_source records the degradation (which the committed
    -card and golden-census pins then catch) and a warning fires."""
    from tools.graftaudit import analyze_program

    def fn(x):
        return x * 2

    p = prog(fn, jnp.ones((4,)))

    class BrokenBackend:
        name = p.entry.name
        donate_argnums = p.entry.donate_argnums
        audit_jaxpr = staticmethod(p.entry.audit_jaxpr)

        @staticmethod
        def audit_lower(spec):
            raise RuntimeError("backend refused")

    broken = AuditProgram(p.name, BrokenBackend, p.spec)
    with pytest.warns(RuntimeWarning, match="degraded to jaxpr"):
        ir_prog = analyze_program(broken, AuditConfig(compile="auto"))
    assert ir_prog.census_source.startswith("jaxpr (compile failed")


def test_steady_train_loss_stays_f32_under_x64(canonical_audit):
    """The sweep fix this PR landed: under x64 the train-step loss used
    to promote to f64 through the dtype-defaulted regularization
    accumulators (zeros(()) in _stack_loss / regularization_score).
    Pin the output dtypes so the promotion can't quietly return."""
    if not jax.config.jax_enable_x64:
        pytest.skip("promotion only exists under x64")
    result, _ = canonical_audit
    for ir_prog in result.irs:
        if not ir_prog.kind.startswith("train_step"):
            continue
        out_dtypes = {str(getattr(getattr(v, "aval", None), "dtype", None))
                      for v in ir_prog.jaxpr.outvars}
        assert "float64" not in out_dtypes, ir_prog.name


def test_full_canonical_audit_wall_time(canonical_audit):
    """Acceptance: the full canonical-set audit (build + both IR phases
    incl. the sharded compiles) fits the CI loop — re-audit the already
    -built set and keep the pure audit under the 60s budget with a wide
    margin (the build itself is amortized module-wide)."""
    import time

    _, programs = canonical_audit
    t0 = time.perf_counter()
    audit_programs(programs, [], CANONICAL_CONFIG)
    dt = time.perf_counter() - t0
    assert dt < 60.0, f"canonical audit took {dt:.1f}s"
