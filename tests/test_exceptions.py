"""Validation error messages (reference
``deeplearning4j-core/src/test/.../exceptions/``: misconfigurations must
fail fast with messages that name the problem and the fix)."""
import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf.input_type import InputType
from deeplearning4j_tpu.nn.conf.multi_layer import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.updaters import Sgd
from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def _build(*layers, itype=None):
    b = (NeuralNetConfiguration.builder().seed(0)
         .updater(Sgd(learning_rate=0.1)).list())
    for l in layers:
        b = b.layer(l)
    if itype is not None:
        b = b.set_input_type(itype)
    return b.build()


def test_missing_n_in_without_input_type():
    conf = _build(DenseLayer(n_out=4, activation="relu"),
                  OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
    with pytest.raises(ValueError, match="n_in|input type"):
        MultiLayerNetwork(conf).init()


def test_unknown_activation_lists_available():
    # validated at CONFIG time (LayerValidation.java parity), not first use
    with pytest.raises((KeyError, ValueError)) as ei:
        _build(DenseLayer(n_out=4, activation="not_an_act"),
               OutputLayer(n_out=2, activation="softmax", loss="mcxent"),
               itype=InputType.feed_forward(3))
    assert "not_an_act" in str(ei.value) or "activation" in str(ei.value)
    assert "relu" in str(ei.value)   # lists what IS available


def test_non_output_last_layer_score():
    conf = _build(DenseLayer(n_out=4, activation="relu"),
                  itype=InputType.feed_forward(3))
    net = MultiLayerNetwork(conf).init()
    with pytest.raises(ValueError, match="output layer"):
        net.score(x=np.zeros((2, 3), np.float32),
                  y=np.zeros((2, 4), np.float32))


def test_graph_cycle_detected():
    from deeplearning4j_tpu.nn.conf.computation_graph import GraphBuilder
    g = GraphBuilder({})
    g.add_inputs("in").set_input_types(InputType.feed_forward(3))
    g.add_layer("a", DenseLayer(n_out=4), "in", "b")
    g.add_layer("b", DenseLayer(n_out=4), "a")
    g.add_layer("out", OutputLayer(n_out=2, activation="softmax",
                                   loss="mcxent"), "b")
    g.set_outputs("out")
    with pytest.raises(ValueError, match="cycle"):
        g.build()


def test_graph_unknown_input_named():
    from deeplearning4j_tpu.nn.conf.computation_graph import GraphBuilder
    g = GraphBuilder({})
    g.add_inputs("in").set_input_types(InputType.feed_forward(3))
    g.add_layer("a", DenseLayer(n_out=4), "nonexistent")
    g.set_outputs("a")
    with pytest.raises(ValueError, match="nonexistent"):
        g.build()


def test_unknown_updater_via_solver():
    net = MultiLayerNetwork(_build(
        OutputLayer(n_out=2, activation="softmax", loss="mcxent"),
        itype=InputType.feed_forward(3))).init()
    from deeplearning4j_tpu.train.solvers import Solver
    with pytest.raises(ValueError, match="available"):
        Solver(net, "quantum_annealing")


def test_wrong_label_width_fails_fast():
    conf = _build(OutputLayer(n_out=3, activation="softmax", loss="mcxent"),
                  itype=InputType.feed_forward(4))
    net = MultiLayerNetwork(conf).init()
    with pytest.raises(Exception):
        net.fit(np.zeros((8, 4), np.float32), np.zeros((8, 7), np.float32))


def test_parameterized_activation_bad_arg_names_activation():
    # 'leakyrelu:abc' must fail naming the activation and expected form,
    # not as a bare float() ValueError (ADVICE r4)
    with pytest.raises(ValueError, match="leakyrelu"):
        _build(DenseLayer(n_out=4, activation="leakyrelu:abc"),
               OutputLayer(n_out=2, activation="softmax", loss="mcxent"),
               itype=InputType.feed_forward(3))


def test_deeply_nested_wrapper_validated():
    # wrappers nested past the old depth-4 cap must still be validated at
    # config time (ADVICE r4: visited-set recursion, no depth cap)
    from deeplearning4j_tpu.nn.layers.recurrent import LastTimeStep
    inner = DenseLayer(n_out=4, activation="not_an_act")
    for _ in range(6):
        inner = LastTimeStep(underlying=inner)
    with pytest.raises((KeyError, ValueError), match="not_an_act|activation"):
        _build(inner,
               OutputLayer(n_out=2, activation="softmax", loss="mcxent"),
               itype=InputType.feed_forward(3))
