"""AutoEncoder / RBM / VAE pretraining, CenterLoss, YOLOv2
(reference: VaeGradientCheckTests, YoloGradientCheckTests, RBM tests).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                NeuralNetConfiguration)
from deeplearning4j_tpu.nn.conf.updaters import Adam, Sgd
from deeplearning4j_tpu.nn.conf.variational import (
    BernoulliReconstructionDistribution, CompositeReconstructionDistribution,
    GaussianReconstructionDistribution, LossFunctionWrapper)
from deeplearning4j_tpu.nn.layers.feedforward import (CenterLossOutputLayer,
                                                      DenseLayer, OutputLayer)
from deeplearning4j_tpu.nn.layers.objdetect import (Yolo2OutputLayer,
                                                    get_predicted_objects)
from deeplearning4j_tpu.nn.layers.pretrain import (AutoEncoder, RBM,
                                                   VariationalAutoencoder)
from deeplearning4j_tpu.utils.gradient_check import (_check_gradients_impl,
                                                     check_gradients)


def _toy_x(n=32, f=8, seed=0, binary=False):
    rng = np.random.default_rng(seed)
    if binary:
        return (rng.random((n, f)) > 0.5).astype(np.float64)
    return rng.standard_normal((n, f))


def _pretrain_grad_check(layer, x, key=None, **kw):
    """Central-difference check of a layer's pretrain_loss."""
    v = layer.init(jax.random.PRNGKey(3), None)
    params = jax.tree_util.tree_map(lambda a: jnp.asarray(a, jnp.float64),
                                    v["params"])
    x = jnp.asarray(x, jnp.float64)

    @jax.jit
    def loss_fn(p):
        return layer.pretrain_loss({"params": p, "state": {}}, x,
                                   key=key, train=key is not None)

    analytic = jax.grad(loss_fn)(params)
    return _check_gradients_impl(loss_fn, params, analytic, 1e-6, 1e-3, 1e-8,
                                 False, kw.get("subset"), 12345)


# ------------------------------------------------------------- autoencoder

def test_autoencoder_gradcheck():
    ae = AutoEncoder(n_in=8, n_out=5, corruption_level=0.0,
                     activation="sigmoid", visible_loss="mse",
                     weight_init="xavier", bias_init=0.0, dtype="float64")
    assert _pretrain_grad_check(ae, _toy_x())


def test_autoencoder_sparsity_gradcheck():
    ae = AutoEncoder(n_in=8, n_out=5, corruption_level=0.0, sparsity=0.1,
                     activation="sigmoid", visible_loss="xent",
                     weight_init="xavier", bias_init=0.0, dtype="float64")
    assert _pretrain_grad_check(ae, _toy_x(binary=True))


def test_autoencoder_pretrain_reduces_reconstruction():
    x = _toy_x(n=100, f=10, binary=True)
    conf = (NeuralNetConfiguration.builder().seed(0)
            .updater(Adam(learning_rate=0.01)).activation("sigmoid")
            .list()
            .layer(AutoEncoder(n_out=6, corruption_level=0.2,
                               visible_loss="xent"))
            .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(10))
            .build())
    net = MultiLayerNetwork(conf).init()
    ae = net.layers[0]
    v0 = {"params": net.params["layer_0"], "state": {}}
    l0 = float(ae.pretrain_loss(v0, jnp.asarray(x), key=None, train=False))
    net.pretrain(x, epochs=200)
    v1 = {"params": net.params["layer_0"], "state": {}}
    l1 = float(ae.pretrain_loss(v1, jnp.asarray(x), key=None, train=False))
    assert l1 < l0 * 0.8


# --------------------------------------------------------------------- rbm

def test_rbm_pretrain_improves_free_energy_gap():
    """After CD-1 training, data free energy should drop relative to noise."""
    rng = np.random.default_rng(1)
    # structured data: two prototype patterns + noise
    protos = (rng.random((2, 12)) > 0.5).astype(np.float64)
    x = protos[rng.integers(0, 2, 200)]
    flip = rng.random(x.shape) < 0.05
    x = np.where(flip, 1 - x, x)

    conf = (NeuralNetConfiguration.builder().seed(0)
            .updater(Sgd(learning_rate=0.05)).list()
            .layer(RBM(n_out=8, k=1))
            .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(12))
            .build())
    net = MultiLayerNetwork(conf).init()
    rbm = net.layers[0]
    noise = (rng.random((200, 12)) > 0.5).astype(np.float64)

    def gap(params):
        fe_data = float(jnp.mean(rbm._free_energy(params, jnp.asarray(x))))
        fe_noise = float(jnp.mean(rbm._free_energy(params, jnp.asarray(noise))))
        return fe_data - fe_noise

    g0 = gap(net.params["layer_0"])
    net.pretrain(x, epochs=100)
    g1 = gap(net.params["layer_0"])
    assert g1 < g0  # data became more probable relative to noise


# --------------------------------------------------------------------- vae

@pytest.mark.parametrize("dist", [
    BernoulliReconstructionDistribution(),
    GaussianReconstructionDistribution(),
    LossFunctionWrapper(loss="mse", activation="identity"),
])
def test_vae_gradcheck_distributions(dist):
    vae = VariationalAutoencoder(
        n_in=6, n_out=3, encoder_layer_sizes=[10], decoder_layer_sizes=[10],
        reconstruction_distribution=dist, activation="tanh",
        weight_init="xavier", bias_init=0.0, dtype="float64")
    binary = isinstance(dist, BernoulliReconstructionDistribution)
    x = _toy_x(n=10, f=6, binary=binary)
    # deterministic ELBO (eps=0) for the numeric check
    assert _pretrain_grad_check(vae, x, key=None, subset=30)


def test_vae_composite_distribution():
    comp = (CompositeReconstructionDistribution()
            .add(4, BernoulliReconstructionDistribution())
            .add(3, GaussianReconstructionDistribution()))
    vae = VariationalAutoencoder(
        n_in=7, n_out=3, encoder_layer_sizes=[8], decoder_layer_sizes=[8],
        reconstruction_distribution=comp, activation="tanh",
        weight_init="xavier", bias_init=0.0, dtype="float64")
    x = np.concatenate([_toy_x(10, 4, binary=True), _toy_x(10, 3)], axis=1)
    assert _pretrain_grad_check(vae, x, key=None, subset=30)


def test_vae_pretrain_and_generate():
    rng = np.random.default_rng(3)
    x = (rng.random((200, 12)) > 0.7).astype(np.float64)
    conf = (NeuralNetConfiguration.builder().seed(0)
            .updater(Adam(learning_rate=0.005)).activation("tanh")
            .list()
            .layer(VariationalAutoencoder(n_out=4, encoder_layer_sizes=[16],
                                          decoder_layer_sizes=[16]))
            .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(12))
            .build())
    net = MultiLayerNetwork(conf).init()
    vae = net.layers[0]
    v = {"params": net.params["layer_0"], "state": {}}
    l0 = float(vae.pretrain_loss(v, jnp.asarray(x), key=None, train=False))
    net.pretrain(x, epochs=150)
    v = {"params": net.params["layer_0"], "state": {}}
    l1 = float(vae.pretrain_loss(v, jnp.asarray(x), key=None, train=False))
    assert l1 < l0
    # latent forward + generation APIs (VAE layer activation = q(z|x) mean)
    z = net.feed_forward(x[:5])[0]
    assert z.shape == (5, 4)
    recon = vae.generate_at_mean_given_z(v, jnp.asarray(z))
    assert recon.shape == (5, 12)
    assert np.all(np.asarray(recon) >= 0) and np.all(np.asarray(recon) <= 1)
    logp = vae.reconstruction_probability(v, jnp.asarray(x[:5]),
                                          jax.random.PRNGKey(0), num_samples=3)
    assert logp.shape == (5,)
    assert np.all(np.isfinite(np.asarray(logp)))


# -------------------------------------------------------------- center loss

def test_center_loss_gradcheck_and_training():
    net_conf = (NeuralNetConfiguration.builder().seed(0)
                .updater(Adam(learning_rate=0.02)).list()
                .layer(DenseLayer(n_out=8, activation="tanh"))
                .layer(CenterLossOutputLayer(n_out=3, activation="softmax",
                                             loss="mcxent", lambda_=0.01))
                .set_input_type(InputType.feed_forward(4))
                .build())
    net = MultiLayerNetwork(net_conf).init()
    rng = np.random.default_rng(0)
    x = rng.standard_normal((15, 4))
    y = np.eye(3)[rng.integers(0, 3, 15)]
    assert check_gradients(net, x, y)
    s0 = net.score(x=x, y=y)
    c_before = np.asarray(net.params["layer_1"]["centers"]).copy()
    net.fit(x, y, epochs=80)
    assert net.score(x=x, y=y) < s0
    # centers moved toward class features
    assert np.abs(np.asarray(net.params["layer_1"]["centers"]) -
                  c_before).max() > 1e-4


# --------------------------------------------------------------------- yolo

def _yolo_setup(seed=0):
    H = W = 4
    B, C = 2, 3
    boxes = [[1.0, 1.5], [2.0, 1.0]]
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((2, H, W, B * (5 + C)))
    labels = np.zeros((2, H, W, 4 + C))
    # one object per image
    for img in range(2):
        r, c = rng.integers(0, H), rng.integers(0, W)
        cx, cy = c + 0.5, r + 0.3
        w, h = 1.2, 0.8
        labels[img, r, c, 0:4] = [cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2]
        labels[img, r, c, 4 + rng.integers(0, C)] = 1.0
    return Yolo2OutputLayer(boxes=boxes), x, labels


def test_yolo_loss_gradcheck():
    layer, x, labels = _yolo_setup()
    x = jnp.asarray(x, jnp.float64)
    labels = jnp.asarray(labels, jnp.float64)

    @jax.jit
    def loss_fn(p):
        return layer.compute_loss({"params": {}, "state": {}}, p["x"], labels)

    params = {"x": x}  # check grads w.r.t. the input activations
    analytic = jax.grad(loss_fn)(params)
    assert _check_gradients_impl(loss_fn, params, analytic, 1e-6, 1e-3, 1e-8,
                                 False, 60, 0)


def test_yolo_training_and_decode():
    from deeplearning4j_tpu.nn.layers.convolution import ConvolutionLayer
    layer, x, labels = _yolo_setup()
    conf = (NeuralNetConfiguration.builder().seed(0)
            .updater(Adam(learning_rate=0.05)).list()
            .layer(ConvolutionLayer(n_out=2 * (5 + 3), kernel_size=(1, 1),
                                    activation="identity"))
            .layer(layer)
            .set_input_type(InputType.convolutional(4, 4, 2 * (5 + 3)))
            .build())
    net = MultiLayerNetwork(conf).init()
    s0 = net.score(x=x, y=labels)
    net.fit(x, labels, epochs=150)
    s1 = net.score(x=x, y=labels)
    assert s1 < s0 * 0.5
    # net.output applies the yolo head → activated [b,H,W,B,5+C]
    dets = get_predicted_objects(net.output(x), threshold=0.0)
    assert len(dets) == 2
    assert dets[0].shape[1] == 6


def test_pretrain_tuple_uses_features_only():
    """Review regression: pretrain((x, y)) must train on x only."""
    x = _toy_x(n=30, f=10, binary=True)
    y = np.eye(3)[np.random.default_rng(0).integers(0, 3, 30)]
    conf = (NeuralNetConfiguration.builder().seed(0)
            .updater(Adam(learning_rate=0.01)).activation("sigmoid").list()
            .layer(AutoEncoder(n_out=6, corruption_level=0.0))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(10)).build())
    net = MultiLayerNetwork(conf).init()
    net.pretrain((x, y), epochs=2)  # would crash/corrupt if y were a batch
    assert np.isfinite(net.get_score())


def test_early_stopping_epoch_counting():
    """Review regression: trainer epochs must not inflate net.epoch."""
    from deeplearning4j_tpu.data.mnist import IrisDataSetIterator
    from deeplearning4j_tpu.earlystopping import (
        EarlyStoppingConfiguration, EarlyStoppingTrainer,
        MaxEpochsTerminationCondition)
    conf = (NeuralNetConfiguration.builder().seed(0)
            .updater(Adam(learning_rate=0.02)).list()
            .layer(DenseLayer(n_out=8, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4)).build())
    net = MultiLayerNetwork(conf).init()
    es = (EarlyStoppingConfiguration.builder()
          .epoch_termination_conditions(MaxEpochsTerminationCondition(4))
          .build())
    # 3 batches/epoch; net.epoch must stay 0 (trainer owns epochs)
    EarlyStoppingTrainer(es, net, IrisDataSetIterator(batch_size=50)).fit()
    assert net.epoch == 0
    assert net.iteration == 4 * 3
