"""Serialized-format stability (reference ``regressiontest/``: load models
saved by old versions, verify config + params + inference parity).  The
golden fixture under tests/resources was written by an earlier build; this
suite must keep passing unchanged as the serializer evolves — if it breaks,
add version-tolerant deserialization, do NOT regenerate the fixture."""
from pathlib import Path

import numpy as np
import pytest

from deeplearning4j_tpu.utils.model_serializer import (
    restore_model, restore_multi_layer_network)

RES = Path(__file__).parent / "resources"


@pytest.fixture(scope="module")
def golden():
    net = restore_multi_layer_network(str(RES / "golden_mlp_v1.zip"))
    io = np.load(RES / "golden_mlp_v1_io.npz")
    return net, io


def test_golden_config_shape(golden):
    net, _ = golden
    assert len(net.layers) == 3
    assert type(net.layers[0]).__name__ == "DenseLayer"
    assert type(net.layers[1]).__name__ == "BatchNormalization"
    assert net.layers[0].n_out == 8
    assert net.conf.seed == 20260730


def test_golden_inference_parity(golden):
    net, io = golden
    out = np.asarray(net.output(io["probe"]))
    np.testing.assert_allclose(out, io["output"], rtol=1e-5, atol=1e-6)


def test_golden_updater_state_restored(golden):
    net, _ = golden
    assert net.opt_state is not None
    # Adam state must carry non-zero moments (training happened pre-save)
    import jax
    leaves = [np.asarray(l) for l in jax.tree_util.tree_leaves(net.opt_state)
              if hasattr(l, "shape") and np.asarray(l).size > 1]
    assert any(np.abs(l).sum() > 0 for l in leaves)


def test_restore_model_sniffs_class(golden):
    net = restore_model(str(RES / "golden_mlp_v1.zip"))
    assert type(net).__name__ == "MultiLayerNetwork"


class TestGoldenGraph:
    """Graph-model format stability (same contract as the MLN fixture)."""

    @pytest.fixture(scope="class")
    def golden(self):
        from deeplearning4j_tpu.utils.model_serializer import \
            restore_computation_graph
        net = restore_computation_graph(str(RES / "golden_graph_v1.zip"))
        io = np.load(RES / "golden_graph_v1_io.npz")
        return net, io

    def test_structure(self, golden):
        net, _ = golden
        assert set(net.conf.vertices) == {"a", "b", "add", "out"}
        assert net.conf.network_inputs == ["in"]

    def test_inference_parity(self, golden):
        net, io = golden
        out = net.output(io["probe"])
        out = np.asarray(out[0] if isinstance(out, list) else out)
        np.testing.assert_allclose(out, io["output"], rtol=1e-5, atol=1e-6)


def test_golden_word2vec_full_model():
    """Format stability for the Word2Vec full-model zip (WordVectorSerializer
    role): the committed fixture must load with identical vectors and
    support query + resumed training.  Do NOT regenerate the fixture — add
    version-tolerant deserialization instead."""
    from deeplearning4j_tpu.nlp.serializer import read_full_model
    m = read_full_model(str(RES / "golden_w2v_v1.zip"))
    io = np.load(RES / "golden_w2v_v1_io.npz", allow_pickle=False)
    assert list(io["words"]) == m.vocab.words()
    np.testing.assert_allclose(np.asarray(m.get_word_vector("alpha")),
                               io["alpha_vec"], atol=1e-6)
    assert abs(m.similarity("alpha", "beta") - float(io["sim_ab"])) < 1e-5
    # resume training on the restored tables must run and stay finite
    from deeplearning4j_tpu.nlp.sentence_iterator import (
        CollectionSentenceIterator)
    m.sentence_iterator = CollectionSentenceIterator(
        ["alpha beta gamma", "delta epsilon zeta"] * 10)
    m.epochs = 1
    m.fit()
    assert np.isfinite(np.asarray(m.lookup_table.syn0)).all()


class TestGoldenCnn:
    """Conv+BN golden fixture (VERDICT r3 item 9): serde stability for the
    layer families most exposed to perf work.  Written by
    tools/make_golden_fixtures.py at round 4; must load unchanged."""

    @pytest.fixture(scope="class")
    def golden(self):
        net = restore_multi_layer_network(str(RES / "golden_cnn_v1.zip"))
        io = np.load(RES / "golden_cnn_v1_io.npz")
        return net, io

    def test_structure(self, golden):
        net, _ = golden
        names = [type(l).__name__ for l in net.layers]
        assert names == ["ConvolutionLayer", "BatchNormalization",
                         "SubsamplingLayer", "DenseLayer", "OutputLayer"]
        assert net.conf.seed == 20260731

    def test_inference_parity(self, golden):
        net, io = golden
        out = np.asarray(net.output(io["probe"]))
        np.testing.assert_allclose(out, io["output"], rtol=1e-5, atol=1e-6)

    def test_bn_running_stats_restored(self, golden):
        net, _ = golden
        # training happened pre-save: BN running stats are non-trivial
        import jax
        stats = [np.asarray(l) for l in jax.tree_util.tree_leaves(net.state)
                 if hasattr(l, "shape")]
        assert stats and any(np.abs(s).sum() > 0 for s in stats)


class TestGoldenTransformer:
    """Transformer golden fixture with KV-cache config (max_cache_len) —
    covers the attention serde surface incl. round-4 fields."""

    @pytest.fixture(scope="class")
    def golden(self):
        net = restore_multi_layer_network(
            str(RES / "golden_transformer_v1.zip"))
        io = np.load(RES / "golden_transformer_v1_io.npz")
        return net, io

    def test_structure_and_cache_config(self, golden):
        net, _ = golden
        names = [type(l).__name__ for l in net.layers]
        assert names == ["EmbeddingSequenceLayer", "PositionalEncodingLayer",
                         "TransformerBlock", "RnnOutputLayer"]
        blk = net.layers[2]
        assert blk.max_cache_len == 24 and blk.causal is True
        assert blk.attn_impl == "reference"

    def test_inference_parity(self, golden):
        net, io = golden
        out = np.asarray(net.output(io["probe"]))
        np.testing.assert_allclose(out, io["output"], rtol=1e-5, atol=1e-6)

    def test_incremental_decode_matches_full(self, golden):
        """The restored model's KV-cache decode path agrees with its full
        forward — the cache config survived serde functionally, not just
        textually."""
        net, io = golden
        probe = io["probe"]
        full = np.asarray(net.output(probe))
        net.rnn_clear_previous_state()
        step_outs = []
        for t in range(probe.shape[1]):
            step_outs.append(np.asarray(
                net.rnn_time_step(probe[:, t:t + 1])))
        inc = np.concatenate(step_outs, axis=1)
        np.testing.assert_allclose(inc, full, rtol=1e-4, atol=1e-5)
