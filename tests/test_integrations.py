"""ML pipeline wrappers, provisioning command generation, UIMA-equivalent
NLP, result DTOs, data formatter, gradient-stats listeners (reference:
dl4j-spark-ml, deeplearning4j-aws, deeplearning4j-nlp-uima,
nn/simple, datasets/rearrange, ParamAndGradientIterationListener)."""
import json

import numpy as np
import pytest

from deeplearning4j_tpu.ml import AutoEncoderEstimator, NetworkEstimator
from deeplearning4j_tpu.nlp import (PosTagger, SentenceSegmenter,
                                    UimaSentenceIterator)
from deeplearning4j_tpu.nn.conf.input_type import InputType
from deeplearning4j_tpu.nn.conf.multi_layer import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.updaters import Adam
from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.simple import (BinaryClassificationResult,
                                          RankClassificationResult)
from deeplearning4j_tpu.provision import (ClusterSpec, StorageTransfer,
                                          TpuClusterSetup)
from deeplearning4j_tpu.train.listeners import \
    ParamAndGradientIterationListener


def _conf(n_in=4, n_out=3):
    return (NeuralNetConfiguration.builder().seed(7)
            .updater(Adam(learning_rate=0.05)).list()
            .layer(DenseLayer(n_out=12, activation="relu"))
            .layer(OutputLayer(n_out=n_out, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(n_in)).build())


def _blobs(n=120, seed=0):
    rng = np.random.default_rng(seed)
    y = rng.integers(0, 3, n)
    x = rng.standard_normal((n, 4)).astype(np.float32) * 0.3
    x[:, :3] += np.eye(3, dtype=np.float32)[y] * 2.0
    return x, y


class TestMlWrappers:
    def test_estimator_fit_predict_score(self):
        x, y = _blobs()
        est = NetworkEstimator(_conf, epochs=30, batch_size=32)
        model = est.fit(x, y)
        assert model.score(x, y) > 0.9
        proba = model.predict_proba(x[:5])
        assert proba.shape == (5, 3)
        np.testing.assert_allclose(proba.sum(1), 1.0, rtol=1e-4)
        assert model.transform(x[:5]).shape == (5, 3)

    def test_params_protocol(self):
        est = NetworkEstimator(_conf, epochs=3)
        assert est.get_params()["epochs"] == 3
        est.set_params(epochs=5)
        assert est.epochs == 5
        with pytest.raises(ValueError, match="unknown param"):
            est.set_params(bogus=1)

    def test_autoencoder_transform_shape(self):
        from deeplearning4j_tpu.nn.layers.pretrain import AutoEncoder

        def conf():
            return (NeuralNetConfiguration.builder().seed(3)
                    .updater(Adam(learning_rate=0.01)).list()
                    .layer(AutoEncoder(n_out=2, activation="tanh"))
                    .set_input_type(InputType.feed_forward(4)).build())

        x, _ = _blobs(60)
        model = AutoEncoderEstimator(conf, epochs=2, batch_size=32).fit(x)
        enc = model.transform(x)
        assert enc.shape == (60, 2)


class TestProvision:
    def test_create_delete_commands(self):
        spec = ClusterSpec(name="trainer", zone="us-central2-b",
                           accelerator_type="v5e-64", project="p1",
                           preemptible=True, tags={"team": "ml"})
        setup = TpuClusterSetup(spec)
        create = setup.create_command()
        assert create[:5] == ["gcloud", "compute", "tpus", "tpu-vm",
                              "create"]
        assert "--accelerator-type=v5e-64" in create
        assert "--project=p1" in create and "--preemptible" in create
        assert "--labels=team=ml" in create
        multi = TpuClusterSetup(ClusterSpec(
            name="m", tags={"b": "2", "a": "1"})).create_command()
        assert "--labels=a=1,b=2" in multi  # one dict-flag occurrence
        assert "delete" in setup.delete_command()
        # dry-run apply returns the command, no execution
        assert setup.apply(execute=False) == create
        script = setup.render()
        assert "tpu-vm create trainer" in script

    def test_ssh_and_storage(self):
        setup = TpuClusterSetup(ClusterSpec(name="x"))
        ssh = setup.ssh_command(worker="0", remote_command="hostname")
        assert ssh[-1] == "hostname" and "--worker=0" in ssh
        st = StorageTransfer("my-bucket")
        up = st.upload_command("/tmp/model.zip", "ckpt/model.zip")
        assert up[-1] == "gs://my-bucket/ckpt/model.zip"
        assert st.run(up, execute=False) == up


class TestUimaEquivalents:
    def test_sentence_segmentation(self):
        segs = SentenceSegmenter().segment(
            "Dr. Smith arrived at 3.5 p.m. sharp. He met J. Doe! Was it "
            "fun? Yes.")
        assert segs == ["Dr. Smith arrived at 3.5 p.m. sharp.",
                        "He met J. Doe!", "Was it fun?", "Yes."]

    def test_sentences_starting_with_numbers_split(self):
        segs = SentenceSegmenter().segment(
            "Tests ran fine. 42 of them passed. All good.")
        assert segs == ["Tests ran fine.", "42 of them passed.",
                        "All good."]

    def test_sentence_iterator(self):
        it = UimaSentenceIterator(["One. Two.", "Three!"])
        assert list(it) == ["One.", "Two.", "Three!"]

    def test_pos_tagger(self):
        tags = dict(PosTagger().tag("the cat quickly ate 42 fishes"))
        assert tags["the"] == "DT"
        assert tags["quickly"] == "RB"
        assert tags["42"] == "CD"
        assert tags["fishes"] == "NNS"


class TestResultDtos:
    def test_binary(self):
        r = BinaryClassificationResult(0.8, threshold=0.6)
        assert r.value and r.to_dict()["value"]
        assert not BinaryClassificationResult(0.3).value

    def test_rank(self):
        r = RankClassificationResult([[0.1, 0.7, 0.2]], ["a", "b", "c"])
        assert r.max_label() == "b"
        assert r.rank() == ["b", "c", "a"]
        assert r.probability(0, "c") == pytest.approx(0.2)
        with pytest.raises(ValueError, match="labels"):
            RankClassificationResult([[0.5, 0.5]], ["only_one"])


class TestFormatter:
    def test_split_directories(self, tmp_path):
        from deeplearning4j_tpu.data import LocalUnstructuredDataFormatter
        src = tmp_path / "raw"
        for label in ("cat", "dog"):
            (src / label).mkdir(parents=True)
            for i in range(10):
                (src / label / f"{i}.txt").write_text("x")
        fmt = LocalUnstructuredDataFormatter(
            tmp_path / "out", src, test_fraction=0.2, seed=1)
        fmt.rearrange()
        assert fmt.get_num_examples_total() == 20
        assert fmt.get_num_test_examples() == 4
        train_cats = list((tmp_path / "out/split/train/cat").iterdir())
        test_cats = list((tmp_path / "out/split/test/cat").iterdir())
        assert len(train_cats) == 8 and len(test_cats) == 2
        # copies by default: source intact
        assert len(list((src / "cat").iterdir())) == 10


class TestGradStatsListener:
    def test_collects_grad_and_param_stats(self, tmp_path):
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        net = MultiLayerNetwork(_conf()).init()
        out = tmp_path / "stats.jsonl"
        lst = ParamAndGradientIterationListener(iterations=1,
                                                output_file=str(out))
        net.set_listeners(lst)
        x, y = _blobs(40)
        net.fit(x, np.eye(3, dtype=np.float32)[y], epochs=2)
        assert len(lst.rows) == 2
        row = lst.rows[-1]
        assert row["grad_norm"] > 0
        assert any(k.startswith("l2_layer_0") for k in row)
        lines = [json.loads(l) for l in out.read_text().splitlines()]
        assert lines[-1]["iteration"] == row["iteration"]


def test_checkpoint_listener_background(tmp_path):
    """Async checkpointing: snapshot + worker-thread write, keep_last
    rotation, restorable artifact."""
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.train.listeners import CheckpointListener
    from deeplearning4j_tpu.utils.model_serializer import \
        restore_multi_layer_network
    net = MultiLayerNetwork(_conf()).init()
    lst = CheckpointListener(str(tmp_path), save_every_n_iterations=2,
                             keep_last=2, background=True)
    net.set_listeners(lst)
    x, y = _blobs(40)
    net.fit(x, np.eye(3, dtype=np.float32)[y], epochs=6)
    lst.wait()
    import os
    files = sorted(os.listdir(tmp_path))
    assert len(files) == 2            # rotation kept the last 2
    back = restore_multi_layer_network(os.path.join(tmp_path, files[-1]))
    assert back.num_params() == net.num_params()


class TestLegacyCompleteness:
    """Minor/legacy reference packages (SURVEY §2.6 completeness listing)."""

    def test_recursive_tree(self):
        """nn/layers/feedforward/autoencoder/recursive/Tree.java surface."""
        from deeplearning4j_tpu.nn.recursive import Tree
        leaves = [Tree(tokens=[w]) for w in ["the", "cat", "sat"]]
        np_ = Tree(); np_.label = "NP"; np_.connect(leaves[:2])
        vp = Tree(); vp.label = "VP"; vp.connect([leaves[2]])
        root = Tree(); root.label = "S"; root.connect([np_, vp])
        assert root.yield_words() == ["the", "cat", "sat"]
        assert [t.tokens[0] for t in root.get_leaves()] == ["the", "cat", "sat"]
        assert root.depth() == 2 and leaves[0].depth() == 0
        # preterminal = exactly one leaf child (reference Tree.java:162)
        assert vp.is_pre_terminal()
        assert not np_.is_pre_terminal() and not root.is_leaf()
        assert root.depth_of(leaves[1]) == 2
        assert leaves[0].parent_in(root) is np_
        assert leaves[0].ancestor(2, root) is root
        np_.error, leaves[0].error = 0.5, 0.25
        assert root.error_sum() == 0.75
        clone = root.clone()
        assert clone.yield_words() == root.yield_words()
        assert clone.children[0] is not np_
        clone.children[0].error = 9.0
        assert root.error_sum() == 0.75  # deep copy

    def test_legacy_vectorizer(self):
        """datasets/vectorizer/Vectorizer.java contract."""
        from deeplearning4j_tpu.data import (CallableVectorizer,
                                             TextCorpusVectorizer)
        ds = CallableVectorizer(
            lambda: (np.ones((4, 3)), np.eye(4))).vectorize()
        assert ds.features.shape == (4, 3) and ds.labels.shape == (4, 4)
        docs = ["good great fine", "bad awful poor", "great good"]
        ds2 = TextCorpusVectorizer(docs, [0, 1, 0], n_classes=2).vectorize()
        assert ds2.features.shape[0] == 3 and ds2.labels.shape == (3, 2)
        assert ds2.features.dtype == np.float32

    def test_distributed_layer_trainer(self):
        """SparkDl4jLayer.java single-layer path over a master."""
        from deeplearning4j_tpu.data.mnist import IrisDataSetIterator
        from deeplearning4j_tpu.nn.conf.updaters import Adam
        from deeplearning4j_tpu.nn.layers.feedforward import OutputLayer
        from deeplearning4j_tpu.parallel import DistributedLayerTrainer
        trainer = DistributedLayerTrainer(
            OutputLayer(n_out=3, activation="softmax", loss="mcxent"),
            input_size=4, updater=Adam(learning_rate=0.1), seed=5)
        trainer.fit(IrisDataSetIterator(batch_size=25), epochs=20)
        ds = next(iter(IrisDataSetIterator(batch_size=150)))
        preds = trainer.predict(ds.features)
        acc = (preds.argmax(1) == np.asarray(ds.labels).argmax(1)).mean()
        assert acc > 0.85, acc
