"""NLP subsystem tests: tokenization, vocab/Huffman, Word2Vec/PV/GloVe
training sanity, serialization round-trips, vectorizers.

Mirrors reference test intents in
``deeplearning4j-nlp/src/test/java/org/deeplearning4j/models/`` (Word2VecTests,
ParagraphVectorsTest, GloveTest) and ``text/`` tokenizer tests, shrunk to
synthetic corpora so CPU runs stay fast.
"""
import os

import numpy as np
import pytest

from deeplearning4j_tpu.nlp import (BagOfWordsVectorizer, BasicLineIterator,
                                    CollectionSentenceIterator,
                                    CommonPreprocessor, DefaultTokenizerFactory,
                                    Glove, LabelledDocument, NGramTokenizer,
                                    ParagraphVectors, SimpleLabelAwareIterator,
                                    TfidfVectorizer, VocabConstructor,
                                    Word2Vec, build_huffman,
                                    make_unigram_table, read_binary,
                                    read_full_model, read_word_vectors,
                                    write_binary, write_full_model,
                                    write_word_vectors)
from deeplearning4j_tpu.nlp.tokenization import DefaultTokenizer
from deeplearning4j_tpu.nlp.vocab import VocabWord


def synthetic_corpus(n=120, seed=7):
    """Two topic clusters: animal words co-occur, tech words co-occur."""
    rng = np.random.default_rng(seed)
    animals = ["cat", "dog", "horse", "cow", "sheep"]
    tech = ["cpu", "gpu", "tpu", "chip", "silicon"]
    out = []
    for _ in range(n):
        pool = animals if rng.random() < 0.5 else tech
        out.append(" ".join(rng.choice(pool, size=8)))
    return out


# ---------------------------------------------------------------------------
# tokenization
# ---------------------------------------------------------------------------

def test_default_tokenizer_and_preprocessor():
    fac = DefaultTokenizerFactory(CommonPreprocessor())
    toks = fac.create("Hello, World! 123 foo-bar").get_tokens()
    assert toks == ["hello", "world", "foo-bar"]


def test_ngram_tokenizer():
    base = DefaultTokenizer("a b c")
    toks = NGramTokenizer(base, 1, 2).get_tokens()
    assert toks == ["a", "b", "c", "a b", "b c"]


def test_sentence_iterators(tmp_path):
    p = tmp_path / "corpus.txt"
    p.write_text("first line\n\nsecond line\n")
    assert list(BasicLineIterator(str(p))) == ["first line", "second line"]
    it = CollectionSentenceIterator(["a", "b"], pre_processor=str.upper)
    assert list(it) == ["A", "B"]
    assert list(it) == ["A", "B"]  # restartable


# ---------------------------------------------------------------------------
# vocab / huffman / tables
# ---------------------------------------------------------------------------

def test_vocab_constructor_min_frequency():
    seqs = [["a", "a", "b"], ["a", "c"]]
    cache = VocabConstructor(min_word_frequency=2).build(seqs)
    assert cache.contains_word("a") and not cache.contains_word("b")
    assert cache.word_frequency("a") == 3
    assert cache.index_of("a") == 0  # most frequent first


def test_huffman_codes_prefix_free_and_frequency_ordered():
    words = [VocabWord(w, count=c, index=i) for i, (w, c) in enumerate(
        [("the", 100), ("of", 60), ("cat", 10), ("dog", 8), ("rare", 1)])]
    build_huffman(words)
    codes = ["".join(map(str, vw.codes)) for vw in words]
    # prefix-free
    for i, a in enumerate(codes):
        for j, b in enumerate(codes):
            if i != j:
                assert not b.startswith(a)
    # frequent words get codes no longer than rare ones
    assert len(words[0].codes) <= len(words[-1].codes)
    # points index internal nodes (< V-1)
    for vw in words:
        assert all(0 <= p < len(words) - 1 for p in vw.points)
        assert len(vw.points) == len(vw.codes)


def test_unigram_table_proportions():
    seqs = [["a"] * 80 + ["b"] * 20]
    cache = VocabConstructor().build(seqs)
    table = make_unigram_table(cache, table_size=10_000)
    frac_a = (table == cache.index_of("a")).mean()
    expected = 80 ** 0.75 / (80 ** 0.75 + 20 ** 0.75)
    assert abs(frac_a - expected) < 0.02


# ---------------------------------------------------------------------------
# word2vec training
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("algo,hs", [("skipgram", False), ("cbow", False),
                                     ("skipgram", True), ("cbow", True)])
def test_word2vec_clusters_topics(algo, hs):
    cbow = algo == "cbow"
    w2v = Word2Vec(sentences=synthetic_corpus(), layer_size=24, window=3,
                   negative=0 if hs else (6 if cbow else 4),
                   use_hierarchic_softmax=hs,
                   epochs=20 if cbow else 5, batch_size=256, seed=11,
                   elements_algorithm=algo,
                   learning_rate=0.025 if cbow else 0.05)
    w2v.fit()
    intra = w2v.similarity("cat", "dog")
    inter = w2v.similarity("cat", "gpu")
    assert intra > inter + 0.1, (algo, hs, intra, inter)
    nearest = w2v.words_nearest("cpu", top_n=2)
    assert set(nearest) <= {"gpu", "tpu", "chip", "silicon"}, nearest


def test_word2vec_query_api():
    w2v = Word2Vec(sentences=synthetic_corpus(40), layer_size=8, epochs=1,
                   negative=2, seed=3)
    w2v.fit()
    assert w2v.has_word("cat") and not w2v.has_word("zebra")
    assert w2v.get_word_vector("cat").shape == (8,)
    assert np.isnan(w2v.similarity("cat", "zebra"))


# ---------------------------------------------------------------------------
# paragraph vectors
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seq_algo", ["dbow", "dm"])
def test_paragraph_vectors_label_separation(seq_algo):
    rng = np.random.default_rng(5)
    docs = []
    for i in range(60):
        pool = (["cat", "dog", "horse", "cow"] if i % 2 == 0
                else ["cpu", "gpu", "tpu", "chip"])
        docs.append(LabelledDocument(" ".join(rng.choice(pool, size=10)),
                                     ["ANIMAL" if i % 2 == 0 else "TECH"]))
    pv = ParagraphVectors(documents=docs, sequence_algorithm=seq_algo,
                          layer_size=16, window=3, negative=3, epochs=3,
                          batch_size=256, seed=9, learning_rate=0.05)
    pv.fit()
    assert set(pv.labels) == {"ANIMAL", "TECH"}
    va = pv.get_label_vector("ANIMAL")
    vt = pv.get_label_vector("TECH")
    cat = pv.get_word_vector("cat")

    def cos(a, b):
        return float(a @ b / (np.linalg.norm(a) * np.linalg.norm(b) + 1e-12))
    assert cos(cat, va) > cos(cat, vt)


def test_paragraph_vectors_infer_vector():
    docs = [LabelledDocument("cat dog cat dog cow", ["A"]),
            LabelledDocument("cpu gpu tpu chip cpu", ["B"])] * 20
    pv = ParagraphVectors(documents=docs, layer_size=12, negative=3,
                          epochs=2, batch_size=128, seed=2)
    pv.fit()
    v = pv.infer_vector("cat dog cow")
    assert v.shape == (12,) and np.isfinite(v).all()
    # inferred animal text sits closer to A than B
    assert (pv.similarity_to_label("cat dog cow cat dog", "A")
            > pv.similarity_to_label("cat dog cow cat dog", "B"))


# ---------------------------------------------------------------------------
# glove
# ---------------------------------------------------------------------------

def test_glove_cooccurrence_counts():
    g = Glove(sentences=["a b c"], window=2, symmetric=True)
    g.vocab = VocabConstructor().build([["a", "b", "c"]])
    cooc = g.count_cooccurrences()
    ia, ib, ic = (g.vocab.index_of(x) for x in "abc")
    assert cooc[(ib, ia)] == 1.0          # adjacent, distance 1
    assert cooc[(ic, ia)] == 0.5          # distance 2 → weight 1/2
    assert cooc[(ia, ib)] == cooc[(ib, ia)]  # symmetric


def test_glove_trains_and_clusters():
    g = Glove(sentences=synthetic_corpus(80), layer_size=16, window=3,
              epochs=8, learning_rate=0.05, seed=13)
    g.fit()
    assert g.similarity("cat", "dog") > g.similarity("cat", "gpu")


# ---------------------------------------------------------------------------
# serialization
# ---------------------------------------------------------------------------

def test_word_vector_txt_roundtrip(tmp_path):
    w2v = Word2Vec(sentences=synthetic_corpus(30), layer_size=8, epochs=1,
                   negative=2, seed=1)
    w2v.fit()
    p = str(tmp_path / "vecs.txt")
    write_word_vectors(w2v, p)
    loaded = read_word_vectors(p)
    assert loaded.vocab.num_words() == w2v.vocab.num_words()
    np.testing.assert_allclose(loaded.get_word_vector("cat"),
                               w2v.get_word_vector("cat"), atol=1e-5)


def test_word_vector_binary_roundtrip(tmp_path):
    w2v = Word2Vec(sentences=synthetic_corpus(30), layer_size=8, epochs=1,
                   negative=2, seed=1)
    w2v.fit()
    p = str(tmp_path / "vecs.bin")
    write_binary(w2v, p)
    loaded = read_binary(p)
    np.testing.assert_allclose(loaded.get_word_vector("dog"),
                               w2v.get_word_vector("dog"), atol=1e-6)


def test_full_model_roundtrip_resumes_training(tmp_path):
    w2v = Word2Vec(sentences=synthetic_corpus(30), layer_size=8, epochs=1,
                   negative=2, seed=1, use_hierarchic_softmax=True)
    w2v.fit()
    p = str(tmp_path / "model.zip")
    write_full_model(w2v, p)
    loaded = read_full_model(p)
    np.testing.assert_allclose(np.asarray(loaded.lookup_table.syn0),
                               np.asarray(w2v.lookup_table.syn0), atol=1e-6)
    vw = loaded.vocab.word_for("cat")
    assert vw.codes == w2v.vocab.word_for("cat").codes
    # resume: training continues from the loaded state
    loaded.sentence_iterator = CollectionSentenceIterator(synthetic_corpus(10))
    loaded.fit()


# ---------------------------------------------------------------------------
# vectorizers
# ---------------------------------------------------------------------------

def test_bag_of_words():
    docs = ["cat dog cat", "dog mouse"]
    bow = BagOfWordsVectorizer().fit(docs)
    m = bow.transform(docs)
    assert m.shape == (2, 3)
    assert m[0, bow.vocab.index_of("cat")] == 2.0
    assert m[1, bow.vocab.index_of("cat")] == 0.0


def test_tfidf_downweights_common_terms():
    docs = ["cat dog", "cat mouse", "cat bird"]
    tf = TfidfVectorizer().fit(docs)
    m = tf.transform(docs)
    assert m[0, tf.vocab.index_of("cat")] == pytest.approx(0.0)  # df=N → idf 0
    assert m[0, tf.vocab.index_of("dog")] > 0


def test_skipgram_tiny_vocab_large_batch_stable():
    """Regression: with a tiny vocabulary a large batch packs many stale
    duplicate updates per word, which diverged before the vocab-size batch
    cap; must stay bounded and learn the topic split."""
    rng = np.random.default_rng(4)
    animals = ["cat", "dog", "cow", "horse", "sheep"]
    tech = ["cpu", "gpu", "tpu", "ram", "disk"]
    sents = [" ".join(rng.choice(animals if rng.random() < 0.5 else tech,
                                 size=8)) for _ in range(400)]
    w2v = Word2Vec(sentences=sents, min_word_frequency=1, epochs=3,
                   layer_size=32, window=4, negative=5, seed=0,
                   batch_size=1024, scan_steps=8)
    w2v.fit()
    s0 = np.asarray(w2v.lookup_table.syn0)
    assert np.isfinite(s0).all() and np.abs(s0).max() < 100.0
    assert w2v.similarity("cat", "dog") > w2v.similarity("cat", "gpu")


def test_bulk_ns_padded_tail_and_tiny_corpus():
    """The corpus-level NS fast path pads its final partial dispatch; the
    padded rows must scatter zeros (n_valids masking), and a corpus far
    smaller than one dispatch must still train."""
    w = Word2Vec(sentences=["a b c d e", "c d e f g", "a c e g"],
                 layer_size=16, window=2, negative=3, epochs=2, seed=7,
                 min_word_frequency=1)
    w.build_vocab()
    before = np.asarray(w.lookup_table.syn0).copy()
    w.fit()
    v = np.asarray(w.get_word_vector("c"))
    assert v.shape == (16,) and np.isfinite(v).all()
    after = np.asarray(w.lookup_table.syn0)
    assert np.isfinite(after).all()
    assert not np.allclose(before, after), "training did not update weights"


def test_bulk_ns_subsampling_and_epoch_cache():
    """Subsampling drops words before windowing and the indexed corpus is
    cached across epochs — both must keep the run finite and learning."""
    rng = np.random.default_rng(1)
    sents = [" ".join("w%d" % i for i in rng.integers(0, 50, 12))
             for _ in range(300)]
    w2 = Word2Vec(sentences=sents, layer_size=16, window=3, negative=5,
                  epochs=3, sampling=1e-3, seed=3, min_word_frequency=1)
    w2.fit()
    assert np.isfinite(w2.similarity("w1", "w2"))
    s0 = np.asarray(w2.lookup_table.syn0)
    assert np.isfinite(s0).all()


def test_bulk_ns_degenerate_sentences():
    """Single-word / empty sentences emit no pairs but must not break the
    chunked emission."""
    w3 = Word2Vec(sentences=["a", "", "a b", "b a b a b a"], layer_size=8,
                  window=5, negative=2, epochs=1, seed=5,
                  min_word_frequency=1)
    w3.fit()
    assert np.isfinite(np.asarray(w3.lookup_table.syn0)).all()


# ---------------------------------------------------------------------------
# bulk-emission equivalence oracle: the corpus-level vectorized pass must
# emit exactly what the per-sentence reference path emits (reference
# obligation: the native-aggregate fast path in SkipGram.java:271-283 is
# semantics-preserving over the scalar loop)
# ---------------------------------------------------------------------------

def _capture_bulk_emission(model, monkeypatch):
    """Run fit() recording every emit_chunk output of the bulk path."""
    from deeplearning4j_tpu.nlp.sequence_vectors import SequenceVectors
    captured = []
    orig = SequenceVectors._bulk_run

    def spy(self, emit_chunk, run_block, S, B, label_width=0):
        def spy_emit(*a):
            out = emit_chunk(*a)
            captured.append(out)
            return out
        return orig(self, spy_emit, run_block, S, B, label_width=label_width)

    monkeypatch.setattr(SequenceVectors, "_bulk_run", spy)
    model.fit()
    monkeypatch.undo()
    return captured


def _capture_generic_sg_pairs(model, monkeypatch):
    """Force the per-sentence loop and record every (ctx, center) pair."""
    from deeplearning4j_tpu.nlp import sequence_vectors as SV
    pairs = []
    orig_add = SV._PairBatcher.add_many

    def spy_add(self, ctx, center, seen=0):
        c = np.asarray(ctx, dtype=np.int64)
        t = np.broadcast_to(np.asarray(center, dtype=np.int64), c.shape)
        pairs.append((c.copy(), t.copy()))
        return orig_add(self, ctx, center, seen)

    monkeypatch.setattr(SV._PairBatcher, "add_many", spy_add)
    monkeypatch.setattr(type(model), "_ns_eligible", lambda self: False)
    model.fit()
    monkeypatch.undo()
    return pairs


def test_bulk_sg_emission_matches_per_sentence_oracle(monkeypatch):
    """For a fixed seed the bulk chunk pass must emit the identical
    (corpus-position, ctx, center) stream as a per-sentence replay — window
    shrink draws, subsampling, and sentence-boundary clipping included."""
    from deeplearning4j_tpu.nlp.sequence_vectors import _window_pairs
    from deeplearning4j_tpu.nlp.vocab import subsample_keep_prob
    sentences = synthetic_corpus(n=300, seed=3)
    kw = dict(layer_size=8, window=3, negative=3, sampling=1e-3, epochs=1,
              seed=11, min_word_frequency=1)
    w = Word2Vec(sentences=sentences, **kw)
    w.build_vocab()
    cap = _capture_bulk_emission(w, monkeypatch)
    bulk = [np.concatenate([c[i] for c in cap]) for i in range(3)]

    # independent per-sentence replay with the bulk stream partitioning
    # (window draws: seed; subsampling: seed+1)
    rng_w = np.random.default_rng(11)
    rng_s = np.random.default_rng(12)
    keep = subsample_keep_prob(w.vocab, w.sampling)
    exp_pos, exp_ctx, exp_cen = [], [], []
    seen = 0
    for seq in w._sequences():
        idxs = np.array([i for i in (w.vocab.index_of(t) for t in seq)
                         if i >= 0], dtype=np.int64)
        if idxs.size == 0:
            continue
        positions = seen + np.arange(idxs.size)
        seen += idxs.size
        m = rng_s.random(idxs.size) < keep[idxs]
        idxs, positions = idxs[m], positions[m]
        if idxs.size < 2:
            continue
        ctx_pos, rows = _window_pairs(rng_w, w.window, idxs.size)
        exp_pos.append(positions[rows])
        exp_ctx.append(idxs[ctx_pos])
        exp_cen.append(idxs[rows])
    assert np.array_equal(bulk[0], np.concatenate(exp_pos))
    assert np.array_equal(bulk[1], np.concatenate(exp_ctx))
    assert np.array_equal(bulk[2], np.concatenate(exp_cen))

    # and the PRODUCTION per-sentence path emits the same pair multiset
    w2 = Word2Vec(sentences=sentences, **kw)
    w2.build_vocab()
    gen = _capture_generic_sg_pairs(w2, monkeypatch)
    gctx = np.concatenate([p[0] for p in gen])
    gcen = np.concatenate([p[1] for p in gen])
    assert np.array_equal(np.sort(bulk[1] * 10**6 + bulk[2]),
                          np.sort(gctx * 10**6 + gcen))


def test_bulk_dbow_emission_matches_generic(monkeypatch):
    """PV-DBOW bulk emission (window pairs + label→word pairs) must match
    the per-sentence path's pair multiset, subsampling included."""
    rng = np.random.default_rng(5)
    docs = []
    for i in range(80):
        pool = (["cat", "dog", "horse", "cow"] if i % 2 == 0
                else ["cpu", "gpu", "tpu", "chip"])
        docs.append(LabelledDocument(" ".join(rng.choice(pool, size=9)),
                                     ["ANIMAL" if i % 2 == 0 else "TECH"]))
    # mixed-corpus hazards: unlabeled docs and 1-token docs must gate
    # identically (per sequence) in both paths or the streams diverge
    docs.insert(10, LabelledDocument("cat dog horse", []))
    docs.insert(20, LabelledDocument("cat", ["ANIMAL"]))
    docs.insert(30, LabelledDocument("gpu", []))
    kw = dict(layer_size=8, window=3, negative=3, sampling=1e-3, epochs=1,
              seed=4, batch_size=128)
    pv = ParagraphVectors(documents=docs, sequence_algorithm="dbow", **kw)
    pv.build_vocab()
    cap = _capture_bulk_emission(pv, monkeypatch)
    bctx = np.concatenate([c[1] for c in cap])
    bcen = np.concatenate([c[2] for c in cap])

    pv2 = ParagraphVectors(documents=docs, sequence_algorithm="dbow", **kw)
    pv2.build_vocab()
    gen = _capture_generic_sg_pairs(pv2, monkeypatch)
    gctx = np.concatenate([p[0] for p in gen])
    gcen = np.concatenate([p[1] for p in gen])
    assert np.array_equal(np.sort(bctx * 10**6 + bcen),
                          np.sort(gctx * 10**6 + gcen))
    # label rows really appear as contexts
    lab_idx = {pv.vocab.index_of("ANIMAL"), pv.vocab.index_of("TECH")}
    assert lab_idx & set(bctx.tolist())


def test_bulk_dm_emission_matches_generic(monkeypatch):
    """PV-DM bulk rows (window + label columns, mask-padded) must match the
    per-sentence CBOW emission row-for-row as (center, sorted-ctx) multisets."""
    from deeplearning4j_tpu.nlp.sequence_vectors import SequenceVectors
    rng = np.random.default_rng(6)
    docs = []
    for i in range(60):
        pool = (["cat", "dog", "horse", "cow"] if i % 2 == 0
                else ["cpu", "gpu", "tpu", "chip"])
        docs.append(LabelledDocument(" ".join(rng.choice(pool, size=8)),
                                     ["ANIMAL" if i % 2 == 0 else "TECH"]))
    kw = dict(layer_size=8, window=2, negative=3, sampling=1e-3, epochs=1,
              seed=8, batch_size=128)
    pv = ParagraphVectors(documents=docs, sequence_algorithm="dm", **kw)
    pv.build_vocab()
    cap = _capture_bulk_emission(pv, monkeypatch)
    bulk_rows = []
    for pos, ctxw, cmask, cen in cap:
        for r in range(len(cen)):
            ctx = tuple(sorted(ctxw[r][cmask[r] > 0].tolist()))
            bulk_rows.append((int(cen[r]), ctx))

    pv2 = ParagraphVectors(documents=docs, sequence_algorithm="dm", **kw)
    pv2.build_vocab()
    gen_rows = []
    orig_emit = SequenceVectors._emit_sequence

    def spy_emit(self, idxs, label_idxs, batcher, rng_, seen=0):
        before = len(self._cbow_buf)
        orig_emit(self, idxs, label_idxs, batcher, rng_, seen)
        for ctx, cen in self._cbow_buf[before:]:
            gen_rows.append((int(cen), tuple(sorted(ctx))))

    monkeypatch.setattr(SequenceVectors, "_emit_sequence", spy_emit)
    monkeypatch.setattr(type(pv2), "_ns_eligible", lambda self: False)
    pv2.fit()
    monkeypatch.undo()
    assert sorted(bulk_rows) == sorted(gen_rows)


def test_paragraph_vectors_rides_bulk_path(monkeypatch):
    """Labeled fits must not fall back to the per-sentence loop anymore."""
    from deeplearning4j_tpu.nlp.sequence_vectors import SequenceVectors
    calls = []
    orig = SequenceVectors._bulk_run

    def spy(self, *a, **k):
        calls.append(k.get("label_width", 0))
        return orig(self, *a, **k)

    monkeypatch.setattr(SequenceVectors, "_bulk_run", spy)
    docs = [LabelledDocument("cat dog cat dog cow", ["A"]),
            LabelledDocument("cpu gpu tpu chip cpu", ["B"])] * 10
    for seq_algo in ("dbow", "dm"):
        for neg in (3, 0):   # ns and hs modes
            pv = ParagraphVectors(documents=docs, sequence_algorithm=seq_algo,
                                  layer_size=8, negative=neg, epochs=1, seed=2)
            pv.fit()
    assert calls == [1, 1, 1, 1]


def test_distributed_word2vec_fan_out():
    """SparkSequenceVectors role (dl4j-spark-nlp): shared vocab, partitioned
    corpus trained per worker, tables averaged — the averaged model must
    still separate the topics."""
    from deeplearning4j_tpu.nlp.distributed_vectors import (
        train_word2vec_distributed)
    rng = np.random.default_rng(6)
    animals = ["cat", "dog", "cow", "horse", "sheep"]
    tech = ["cpu", "gpu", "tpu", "ram", "disk"]
    sents = [" ".join(rng.choice(animals if rng.random() < 0.5 else tech,
                                 size=8)) for _ in range(400)]
    m = train_word2vec_distributed(sents, num_workers=3, layer_size=24,
                                   window=4, negative=5, epochs=3, seed=0,
                                   min_word_frequency=1)
    assert m.vocab.num_words() == 10
    assert m.similarity("cat", "dog") > m.similarity("cat", "gpu")
    s0 = np.asarray(m.lookup_table.syn0)
    assert np.isfinite(s0).all()
    # single-worker path degenerates to plain fit
    m1 = train_word2vec_distributed(sents[:50], num_workers=1, layer_size=8,
                                    window=2, negative=3, epochs=1, seed=0,
                                    min_word_frequency=1)
    assert np.isfinite(np.asarray(m1.lookup_table.syn0)).all()


def _topic_corpus(n=120, seed=6):
    rng = np.random.default_rng(seed)
    animals = ["cat", "dog", "cow", "horse", "sheep"]
    tech = ["cpu", "gpu", "tpu", "ram", "disk"]
    return [" ".join(rng.choice(animals if rng.random() < 0.5 else tech,
                                size=8)) for _ in range(n)]


def test_multiprocess_word2vec_matches_thread_version(tmp_path):
    """VERDICT r3 item 5: distributed embeddings over OS processes
    (dl4j-spark-nlp Word2Vec.java:61 executor topology).  Same sharding,
    same shared vocab, same initial tables ⇒ the process-based averaged
    tables must match the thread-based run to float noise, and workers
    report a words/sec figure."""
    from deeplearning4j_tpu.nlp.distributed_vectors import (
        train_word2vec_distributed, train_word2vec_multiprocess)
    sents = _topic_corpus()
    kw = dict(layer_size=16, window=3, negative=4, epochs=2, seed=0,
              min_word_frequency=1)
    m_thread = train_word2vec_distributed(sents, num_workers=2, **kw)
    # JAX_ENABLE_X64 matches this test process (conftest enables x64, which
    # changes accumulation dtypes) so thread and process runs are comparable
    m_proc = train_word2vec_multiprocess(
        sents, num_workers=2,
        worker_env={"JAX_PLATFORMS": "cpu", "JAX_ENABLE_X64": "1"},
        jobdir=str(tmp_path), **kw)
    np.testing.assert_allclose(np.asarray(m_proc.lookup_table.syn0),
                               np.asarray(m_thread.lookup_table.syn0),
                               atol=2e-4)
    assert m_proc.similarity("cat", "dog") > m_proc.similarity("cat", "gpu")


def test_multiprocess_word2vec_retry(tmp_path):
    """A worker that dies at start is respawned and its shard re-executed
    (stateless shards, the RDD-lineage contract)."""
    from deeplearning4j_tpu.nlp.distributed_vectors import (
        Word2VecProcessMaster)
    from deeplearning4j_tpu.nlp.word2vec import Word2Vec
    sents = _topic_corpus(n=60)
    model = Word2Vec(sentences=sents, layer_size=8, window=2, negative=3,
                     epochs=1, seed=0, min_word_frequency=1)
    master = Word2VecProcessMaster(
        num_workers=2, worker_env={"JAX_PLATFORMS": "cpu"}, timeout=120.0,
        fault_injection={"die_at_start": [1]})
    master.fit(model, jobdir=str(tmp_path))
    assert master.retried_workers == {1}
    assert all(r.get("words_per_sec", 0) > 0 for r in master.last_results)
    assert np.isfinite(np.asarray(model.lookup_table.syn0)).all()
