"""CJK tokenizers, inverted index / keyword extraction, Viterbi, moving
window + LFW iterators (reference: deeplearning4j-nlp-chinese/-japanese/
-korean factories, text/invertedindex, util/Viterbi.java,
MovingWindowBaseDataSetIterator, LFWDataSetIterator)."""
import numpy as np
import pytest

from deeplearning4j_tpu.data import (DataSet, LFWDataSetIterator,
                                     MovingWindowDataSetIterator)
from deeplearning4j_tpu.nlp import (ChineseTokenizerFactory, InvertedIndex,
                                    JapaneseTokenizerFactory,
                                    KeywordExtractor, KoreanTokenizerFactory)
from deeplearning4j_tpu.utils.viterbi import Viterbi, viterbi_decode


class TestCjkTokenizers:
    def test_chinese_known_words(self):
        toks = ChineseTokenizerFactory().create("我爱北京 hello").get_tokens()
        # bundled lexicon: 北京 is one word; OOV 爱 falls out per char
        assert toks == ["我", "爱", "北京", "hello"]

    def test_chinese_dictionary_longest_match(self):
        tf = ChineseTokenizerFactory(dictionary=["北京", "天安门"])
        assert tf.create("我爱北京天安门").get_tokens() == \
            ["我", "爱", "北京", "天安门"]

    def test_japanese_lattice_runs(self):
        toks = JapaneseTokenizerFactory().create(
            "東京タワーへいく").get_tokens()
        # 東京 from the lexicon, タワー as a katakana run, へ particle split
        assert toks[0] == "東京" and "タワー" in toks and "へ" in toks

    def test_korean_morphological_lattice(self):
        # morphological (default, round 4): eojeol -> stem + josa/endings,
        # the reference KoreanTokenizerTest granularity
        toks = KoreanTokenizerFactory().create("나는 학교에 간다").get_tokens()
        assert toks == ["나", "는", "학교", "에", "간", "다"]
        # unknown stems merge back into one token; the particle splits off
        toks = KoreanTokenizerFactory().create("김철수가 왔다").get_tokens()
        assert toks == ["김철수", "가", "왔", "다"]

    def test_korean_particle_strip_legacy(self):
        toks = KoreanTokenizerFactory(morphological=False).create(
            "나는 학교에 간다").get_tokens()
        assert toks == ["나", "학교", "간다"]
        raw = KoreanTokenizerFactory(strip_particles=False,
                                     morphological=False).create(
            "나는 학교에 간다").get_tokens()
        assert raw == ["나는", "학교에", "간다"]


class TestInvertedIndex:
    def _index(self):
        ix = InvertedIndex()
        ix.add_documents(["the quick brown fox",
                          "the lazy dog",
                          "quick quick dog"])
        return ix

    def test_postings_and_counts(self):
        ix = self._index()
        assert ix.num_documents() == 3
        assert ix.total_words() == 10
        assert ix.documents("quick") == [0, 2]
        assert ix.term_frequency("quick", 2) == 2
        assert ix.document_frequency("the") == 2
        assert ix.positions("dog", 2) == [2]

    def test_search_ranked(self):
        ix = self._index()
        assert ix.search("quick") == [2, 0]       # tf 2 beats tf 1
        assert ix.search("quick", "dog") == [2]   # conjunctive
        assert ix.search("missing") == []

    def test_keywords(self):
        ix = self._index()
        kws = KeywordExtractor(ix).keywords(0, top_n=2)
        words = [w for w, _ in kws]
        # 'the' appears in 2/3 docs -> low idf; fox/brown are doc-specific
        assert "the" not in words
        assert set(words) <= {"quick", "brown", "fox"}
        corpus = KeywordExtractor(ix).corpus_keywords(top_n=3)
        assert all(s > 0 for _, s in corpus)


class TestViterbi:
    def test_decode_recovers_clean_path(self):
        # 2 states, near-deterministic emissions
        e = np.array([[0.9, 0.1], [0.8, 0.2], [0.1, 0.9], [0.2, 0.8]])
        t = np.array([[0.7, 0.3], [0.3, 0.7]])
        path, logp = viterbi_decode(e, t)
        assert path.tolist() == [0, 0, 1, 1]
        assert np.isfinite(logp) and logp < 0

    def test_transition_bias_smooths_noise(self):
        # a single noisy frame is overridden by sticky transitions
        e = np.array([[0.9, 0.1], [0.45, 0.55], [0.9, 0.1], [0.9, 0.1]])
        v = Viterbi([0, 1])  # default 0.75 self-transition
        labels, _ = v.decode(e)
        assert labels.tolist() == [0, 0, 0, 0]

    def test_batch_decode_matches_single(self):
        rng = np.random.default_rng(3)
        e = rng.uniform(0.05, 1.0, (4, 7, 3))
        e /= e.sum(-1, keepdims=True)
        v = Viterbi(["a", "b", "c"])
        paths, logps = v.decode_batch(e)
        assert paths.shape == (4, 7)
        for i in range(4):
            single, lp = viterbi_decode(e[i], v.transitions)
            np.testing.assert_array_equal(paths[i], single)
            assert abs(lp - float(logps[i])) < 1e-4


class TestMovingWindowAndLfw:
    def test_moving_window_tiles(self):
        feats = np.arange(2 * 4 * 4, dtype=np.float32).reshape(2, 4, 4)
        labels = np.eye(2, dtype=np.float32)
        it = MovingWindowDataSetIterator(DataSet(feats, labels), batch_size=8,
                                         window_rows=2, window_cols=2)
        batches = list(it)
        x = np.concatenate([np.asarray(b.features) for b in batches])
        y = np.concatenate([np.asarray(b.labels) for b in batches])
        assert x.shape == (8, 2, 2)      # 4 windows x 2 examples
        assert y.shape == (8, 2)
        np.testing.assert_array_equal(x[0], feats[0, :2, :2])
        np.testing.assert_array_equal(x[-1], feats[1, 2:, 2:])

    def test_moving_window_rejects_flat(self):
        with pytest.raises(ValueError, match="image features"):
            MovingWindowDataSetIterator(
                DataSet(np.zeros((2, 10)), np.zeros((2, 2))), 4, 2, 2)

    def test_lfw_synthetic(self):
        it = LFWDataSetIterator(batch_size=16, hw=32, num_labels=5,
                                num_examples=64)
        assert it.synthetic
        b = next(iter(it))
        assert np.asarray(b.features).shape == (16, 32, 32, 3)
        assert np.asarray(b.labels).shape == (16, 5)
        assert 0.0 <= float(np.asarray(b.features).min())
        assert float(np.asarray(b.features).max()) <= 1.0


class TestTimeSeriesUtils:
    def test_reverse_with_mask_keeps_padding(self):
        from deeplearning4j_tpu.utils.time_series import reverse_time_series
        x = np.arange(2 * 4 * 1, dtype=np.float32).reshape(2, 4, 1)
        mask = np.array([[1, 1, 1, 0], [1, 1, 1, 1]], np.float32)
        out = np.asarray(reverse_time_series(x, mask))
        np.testing.assert_allclose(out[0, :, 0], [2, 1, 0, 3])  # pad stays
        np.testing.assert_allclose(out[1, :, 0], [7, 6, 5, 4])

    def test_last_time_step(self):
        from deeplearning4j_tpu.utils.time_series import get_last_time_step
        x = np.arange(2 * 3 * 2, dtype=np.float32).reshape(2, 3, 2)
        mask = np.array([[1, 1, 0], [1, 1, 1]], np.float32)
        out = np.asarray(get_last_time_step(x, mask))
        np.testing.assert_allclose(out[0], x[0, 1])
        np.testing.assert_allclose(out[1], x[1, 2])

    def test_moving_window_matrix(self):
        from deeplearning4j_tpu.utils.time_series import moving_window_matrix
        x = np.arange(10, dtype=np.float32).reshape(5, 2)
        w = moving_window_matrix(x, window=3, stride=1)
        assert w.shape == (3, 3, 2)
        np.testing.assert_allclose(w[1], x[1:4])
        with pytest.raises(ValueError, match="window"):
            moving_window_matrix(x, window=9)

    def test_reshape_mask(self):
        from deeplearning4j_tpu.utils.time_series import \
            reshape_time_series_mask
        m = np.array([[1, 0], [1, 1]], np.float32)
        out = np.asarray(reshape_time_series_mask(m, 3))
        assert out.shape == (4, 3)
        np.testing.assert_allclose(out[1], 0)


class TestMovingWindow:
    """text/movingwindow package (Windows/Window/WindowConverter/
    ContextLabelRetriever)."""

    def test_windows_padding_and_focus(self):
        from deeplearning4j_tpu.nlp.moving_window import windows
        ws = windows("the quick brown fox", window_size=5)
        assert len(ws) == 4
        assert ws[0].words == ["<s>", "<s>", "the", "quick", "brown"]
        assert ws[0].focus_word() == "the"
        assert ws[-1].focus_word() == "fox"
        assert ws[-1].words == ["quick", "brown", "fox", "</s>", "</s>"]
        with pytest.raises(ValueError, match="odd"):
            from deeplearning4j_tpu.nlp.moving_window import windows as _w
            _w("a b c", window_size=4)

    def test_window_converter(self):
        from deeplearning4j_tpu.nlp.moving_window import (WindowConverter,
                                                          windows)

        class _Vec:
            class lookup_table:
                syn0 = np.zeros((3, 4))
            @staticmethod
            def get_word_vector(w):
                return {"a": np.ones(4), "b": np.full(4, 2.0)}.get(w)

        ws = windows("a b a", window_size=3)
        m = WindowConverter.as_example_matrix(ws[1], _Vec())
        assert m.shape == (3, 4)
        np.testing.assert_array_equal(m[0], np.ones(4))
        np.testing.assert_array_equal(m[1], np.full(4, 2.0))
        flat = WindowConverter.as_example_array(ws[1], _Vec(), normalize=True)
        assert flat.shape == (12,)
        assert abs(np.linalg.norm(flat) - 1.0) < 1e-6

    def test_context_label_retriever(self):
        from deeplearning4j_tpu.nlp.moving_window import ContextLabelRetriever
        text, spans = ContextLabelRetriever.string_with_labels(
            "the <PER> john smith </PER> went to <LOC> paris </LOC> today")
        assert text == "the john smith went to paris today"
        assert spans == {"PER": [(1, 3)], "LOC": [(5, 6)]}
        # repeated labels keep every span (multimap semantics)
        _, multi = ContextLabelRetriever.string_with_labels(
            "<PER> john </PER> met <PER> mary </PER>")
        assert multi == {"PER": [(0, 1), (2, 3)]}
        with pytest.raises(ValueError, match="unclosed"):
            ContextLabelRetriever.string_with_labels("<PER> john")
        with pytest.raises(ValueError, match="mismatched"):
            ContextLabelRetriever.string_with_labels("<PER> x </LOC>")

    def test_window_boundary_flags(self):
        from deeplearning4j_tpu.nlp.moving_window import windows
        ws = windows("a b c d e", window_size=3)
        assert ws[0].is_begin_label() and not ws[0].is_end_label()
        assert not ws[2].is_begin_label() and not ws[2].is_end_label()
        assert ws[-1].is_end_label() and not ws[-1].is_begin_label()


class TestLatticeSegmentation:
    """VERDICT item 9: dictionary-based CJK segmentation (bundled lexicon +
    unigram Viterbi lattice; reference vendors ansj/kuromoji)."""

    def test_chinese_lattice_non_trivial(self):
        from deeplearning4j_tpu.nlp.cjk import ChineseTokenizerFactory
        zh = ChineseTokenizerFactory()
        # 北京大学 is a single dictionary word in the ansj-derived tier
        # (round 4) — the institution name stays whole
        assert zh.create("我们今天在北京大学学习机器学习").get_tokens() == \
            ["我们", "今天", "在", "北京大学", "学习", "机器学习"]
        # the classic ambiguity greedy longest-match gets wrong:
        # 研究生 would strand 命 as an OOV char
        assert zh.create("研究生命科学").get_tokens() == ["研究", "生命", "科学"]
        # but 研究生 wins when the context calls for it
        toks = zh.create("他是研究生").get_tokens()
        assert "研究生" in toks

    def test_chinese_user_dictionary_outranks(self):
        from deeplearning4j_tpu.nlp.cjk import ChineseTokenizerFactory
        zh = ChineseTokenizerFactory(dictionary=["北京大学"])
        assert "北京大学" in zh.create("我们在北京大学学习").get_tokens()

    def test_japanese_lattice_non_trivial(self):
        from deeplearning4j_tpu.nlp.cjk import JapaneseTokenizerFactory
        ja = JapaneseTokenizerFactory()
        toks = ja.create("私は東京大学で機械学習を勉強しています").get_tokens()
        for w in ("私", "は", "東京", "大学", "で", "機械学習", "を", "勉強"):
            assert w in toks, (w, toks)
        # unknown katakana run survives as one token
        toks2 = ja.create("コンピュータで計算する").get_tokens()
        assert toks2[:2] == ["コンピュータ", "で"] and "計算" in toks2

    def test_mixed_scripts_and_punctuation(self):
        from deeplearning4j_tpu.nlp.cjk import ChineseTokenizerFactory
        toks = ChineseTokenizerFactory().create(
            "人工智能改变世界 hello world").get_tokens()
        assert "人工智能" in toks and "hello" in toks and "world" in toks


class TestSerializerFormats:
    """csv + gzip + static loading (WordVectorSerializer.java format
    matrix)."""

    def _model(self):
        import numpy as np
        from deeplearning4j_tpu.nlp.word2vec import Word2Vec
        w = Word2Vec(sentences=["alpha beta gamma delta"] * 30,
                     layer_size=12, window=2, negative=3, epochs=1,
                     min_word_frequency=1, seed=0)
        w.fit()
        return w

    def test_csv_roundtrip(self, tmp_path):
        import numpy as np
        from deeplearning4j_tpu.nlp import serializer as S
        m = self._model()
        p = str(tmp_path / "vecs.csv")
        S.write_csv(m, p)
        m2 = S.read_csv(p)
        assert m2.vocab.words() == m.vocab.words()
        np.testing.assert_allclose(np.asarray(m2.lookup_table.syn0),
                                   np.asarray(m.lookup_table.syn0),
                                   atol=1e-5)

    def test_gzip_txt_and_csv(self, tmp_path):
        import numpy as np
        from deeplearning4j_tpu.nlp import serializer as S
        m = self._model()
        pt = str(tmp_path / "vecs.txt.gz")
        pc = str(tmp_path / "vecs.csv.gz")
        S.write_word_vectors(m, pt)
        S.write_csv(m, pc)
        import gzip as _g
        assert open(pt, "rb").read(2) == b"\x1f\x8b"
        for p, rd in ((pt, S.read_word_vectors), (pc, S.read_csv)):
            m2 = rd(p)
            np.testing.assert_allclose(np.asarray(m2.lookup_table.syn0),
                                       np.asarray(m.lookup_table.syn0),
                                       atol=1e-5)

    def test_load_static_model_sniffs_all_formats(self, tmp_path):
        import numpy as np
        from deeplearning4j_tpu.nlp import serializer as S
        m = self._model()
        paths = {
            "txt": str(tmp_path / "a.txt"),
            "csv": str(tmp_path / "a.csv"),
            "bin": str(tmp_path / "a.bin"),
            "zip": str(tmp_path / "a.zip"),
            "txt.gz": str(tmp_path / "a.txt.gz"),
        }
        S.write_word_vectors(m, paths["txt"])
        S.write_csv(m, paths["csv"])
        S.write_binary(m, paths["bin"])
        S.write_full_model(m, paths["zip"])
        S.write_word_vectors(m, paths["txt.gz"])
        for kind, p in paths.items():
            m2 = S.load_static_model(p)
            np.testing.assert_allclose(np.asarray(m2.lookup_table.syn0),
                                       np.asarray(m.lookup_table.syn0),
                                       atol=1e-5, err_msg=kind)

    def test_load_static_model_ascii_binary_not_misrouted(self, tmp_path):
        """A binary model whose packed float32 payload happens to decode as
        UTF-8 (printable ASCII bytes) must still load as binary — the txt
        sniff falls back when the rows don't parse as 'word v1 v2 ...'."""
        import numpy as np
        from deeplearning4j_tpu.nlp import serializer as S
        row0, row1 = (np.frombuffer(b"ABCDEFGH", dtype="<f4"),
                      np.frombuffer(b"IJKLMNOP", dtype="<f4"))
        p = str(tmp_path / "ascii.bin")
        with open(p, "wb") as f:
            f.write(b"2 2\n")
            f.write(b"aa " + row0.tobytes() + b"\n")
            f.write(b"bb " + row1.tobytes() + b"\n")
        m2 = S.load_static_model(p)
        np.testing.assert_allclose(np.asarray(m2.lookup_table.syn0),
                                   np.stack([row0, row1]))
        assert m2.vocab.word_at_index(0) == "aa"

    def test_load_static_model_truncated_sniff_window_widens(self, tmp_path):
        """A txt file whose first data row overflows the 256-byte sniff
        window with the cut landing mid-value ('word 0.1 0.2 ... 1e|-05')
        must widen the window instead of misrouting to read_binary
        (ADVICE r3: a '1e' / '-' prefix fails float-parse but proves
        nothing about the format)."""
        import numpy as np
        from deeplearning4j_tpu.nlp import serializer as S
        # first value token: 255 chars, positioned so the 256-byte window
        # (after "aa ") cuts it to a '...e-' prefix — float() fails on it
        tok = "1." + "2" * 249 + "e-05"
        p = str(tmp_path / "wide.txt")
        with open(p, "w") as f:
            f.write("2 2\n")
            f.write(f"aa {tok} 3.5\n")
            f.write("bb 1.0 2.0\n")
        with open(p, "rb") as f:
            f.readline()
            window = f.read(256)
        assert b"\n" not in window and window.decode().split()[-1][-2:] == "e-"
        m2 = S.load_static_model(p)
        np.testing.assert_allclose(
            np.asarray(m2.lookup_table.syn0),
            np.array([[float(tok), 3.5], [1.0, 2.0]], np.float32))

    def test_csv_rejects_comma_words(self, tmp_path):
        import pytest
        from deeplearning4j_tpu.nlp import serializer as S
        from deeplearning4j_tpu.nlp.vocab import VocabCache, VocabWord
        import numpy as np
        m = self._model()
        m.vocab.add_token(VocabWord("bad,word"))
        from deeplearning4j_tpu.nlp.lookup_table import InMemoryLookupTable
        m.lookup_table = InMemoryLookupTable(m.vocab, 12)
        m.lookup_table.reset_weights()
        with pytest.raises(ValueError, match="comma"):
            S.write_csv(m, str(tmp_path / "x.csv"))


class TestCjkSegmentationQuality:
    """Segmentation accuracy is measured against tagged gold fixtures, not
    asserted by example (VERDICT r2 item 8 — the reference's vendored
    ansj/kuromoji dictionaries make quality implicit; here the bundled
    lexicon's quality is a tested floor)."""

    @staticmethod
    def _spans(words):
        out, p = set(), 0
        for w in words:
            out.add((p, p + len(w)))
            p += len(w)
        return out

    def _f1(self, path, factory):
        import os
        tp = fp = fn = 0
        n_sent = 0
        base = os.path.join(os.path.dirname(__file__), "resources", path)
        with open(base, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                gold = line.split()
                pred = factory.create("".join(gold)).get_tokens()
                assert "".join(pred) == "".join(gold)  # lossless cover
                g, p = self._spans(gold), self._spans(pred)
                tp += len(g & p)
                fp += len(p - g)
                fn += len(g - p)
                n_sent += 1
        assert n_sent >= 20
        prec, rec = tp / max(tp + fp, 1), tp / max(tp + fn, 1)
        return 2 * prec * rec / max(prec + rec, 1e-9)

    def test_chinese_segmentation_f1_floor(self):
        # lexicon data derived from the ansj core dictionary (independent
        # of this fixture's author — the r3 circularity is gone both ways).
        # Round 5 grew the fixture 29 -> 226 hand-authored sentences
        # (VERDICT r4: fixture power); measured 0.9246 — the residual is
        # genuine lexicalization ambiguity (很多 vs 很|多, 这家 vs 这|家)
        # where the CTB-style gold and ansj-derived lexicon legitimately
        # disagree, not segmentation error.  Floor set from the measured
        # value, with the old saturated 0.95 fixture retired.
        from deeplearning4j_tpu.nlp.cjk import ChineseTokenizerFactory
        f1 = self._f1("cjk_gold_zh.txt", ChineseTokenizerFactory())
        assert f1 >= 0.90, f"zh segmentation F1 regressed: {f1:.3f}"

    def test_japanese_segmentation_f1_floor(self):
        from deeplearning4j_tpu.nlp.cjk import JapaneseTokenizerFactory
        f1 = self._f1("cjk_gold_ja.txt", JapaneseTokenizerFactory())
        assert f1 >= 0.97, f"ja segmentation F1 regressed: {f1:.3f}"

    def test_japanese_heldout_bocchan_f1_floor(self):
        """VERDICT r3 item 6: F1 on text the lexicon never saw — the
        held-out 20% of the IPADIC-tokenized kuromoji corpus (250
        sentences; the lexicon trained on the other 80%,
        tools/build_cjk_lexicons.py).  Round 5 added the bigram transition
        lattice (PMI bonuses, dev-split-selected beta — ja_bigram.tsv);
        measured 0.9071 (up from 0.904 unigram).  The VERDICT r4 0.92
        target was not reached: the residual errors are OOV content words
        and IPADIC-specific function-morpheme conventions, which bigrams
        learned from the same 46k-token novel cannot supply (error
        analysis in the round-5 notes).  Deterministic."""
        from deeplearning4j_tpu.nlp.cjk import JapaneseTokenizerFactory
        f1 = self._f1("cjk_gold_ja_bocchan.txt", JapaneseTokenizerFactory())
        assert f1 >= 0.90, f"ja held-out F1 regressed: {f1:.3f}"

    def test_japanese_kuromoji_decompound_f1_floor(self):
        """Hand-written gold by the kuromoji authors (search-mode compound
        decomposition — their own 'weaknesses' cases).  Fully independent;
        hard: unknown-compound splitting without a 400k dictionary.
        Measured 0.8705 in round 5 (0.766 in round 4; 0.385 before the
        round-4 kanji-pair heuristic) — the round-5 gain is the broad
        general-purpose katakana loanword/name band in lexicons.py:
        compound splitting needs the lattice to KNOW constituent words,
        the role IPADIC's 400k entries play for kuromoji."""
        from deeplearning4j_tpu.nlp.cjk import JapaneseTokenizerFactory
        f1 = self._f1("cjk_gold_ja_kuromoji.txt", JapaneseTokenizerFactory())
        assert f1 >= 0.85, f"ja decompound F1 regressed: {f1:.3f}"

    def test_korean_segmentation_f1_floor(self):
        """Korean lattice (new in round 4; the reference wraps KOMORAN).
        Fixture format: input<TAB>gold (Korean keeps eojeol spacing).
        Line 1 is the reference's own KoreanTokenizerTest gold."""
        import os
        from deeplearning4j_tpu.nlp.cjk import KoreanTokenizerFactory
        fac = KoreanTokenizerFactory()
        tp = fp = fn = 0
        n_sent = 0
        base = os.path.join(os.path.dirname(__file__), "resources",
                            "cjk_gold_ko.txt")
        with open(base, encoding="utf-8") as f:
            for line in f:
                line = line.rstrip("\n")
                if not line or line.startswith("#"):
                    continue
                inp, _, goldtxt = line.partition("\t")
                gold = goldtxt.split()
                pred = fac.create(inp).get_tokens()
                assert "".join(pred) == "".join(gold)
                g, p = self._spans(gold), self._spans(pred)
                tp += len(g & p)
                fp += len(p - g)
                fn += len(g - p)
                n_sent += 1
        assert n_sent >= 100            # round-5 fixture size (r4 item 8)
        prec, rec = tp / max(tp + fp, 1), tp / max(tp + fn, 1)
        f1 = 2 * prec * rec / max(prec + rec, 1e-9)
        assert f1 >= 0.95, f"ko segmentation F1 regressed: {f1:.3f}"

    def test_lexicon_scale(self):
        """Curated bands + corpus-derived tiers (round 4: ansj-derived zh
        frequencies, IPADIC-corpus-learned ja frequencies) — the quality
        floors above are what actually matters."""
        from deeplearning4j_tpu.nlp.lexicons import (CHINESE_LEXICON,
                                                     JAPANESE_LEXICON,
                                                     KOREAN_LEXICON)
        assert len(CHINESE_LEXICON) >= 35000
        assert len(JAPANESE_LEXICON) >= 6000
        assert len(KOREAN_LEXICON) >= 2000   # round-5 curated tier (r4 item 8)
        # every entry carries a sane log-prob band
        for lex in (CHINESE_LEXICON, JAPANESE_LEXICON, KOREAN_LEXICON):
            assert all(-10.0 < s < 0.0 for s in lex.values())
        # max-merge: a word listed in several thematic bands keeps its
        # HIGHEST band; ください is a top-frequency function word and must
        # not be downgraded by re-listing (して is deliberately GONE —
        # round 4 aligned granularity with IPADIC morphemes: し|て)
        assert JAPANESE_LEXICON["ください"] >= -4.0
        assert "して" not in JAPANESE_LEXICON
        assert JAPANESE_LEXICON["し"] >= -4.0 and JAPANESE_LEXICON["て"] >= -4.0
        # words earlier reorganizations once dropped — pinned
        for w in ("生活", "いい", "良い"):
            assert w in JAPANESE_LEXICON, w
        for w in ("生命", "老师", "学生"):
            assert w in CHINESE_LEXICON, w


class TestBigramLattice:
    """Word-state Viterbi with transition bonuses (round 5 — the ansj
    NgramLibrary / kuromoji ViterbiSearcher transition-cost mechanism)."""

    def test_transition_resolves_unigram_tie(self):
        from deeplearning4j_tpu.nlp.cjk import lattice_segment
        # two tilings with EQUAL unigram score; only the learned
        # transition (B after A) breaks the tie toward A|BC
        lex = {"ab": -5.0, "c": -5.0, "a": -5.0, "bc": -5.0}
        uni = lattice_segment("abc", lex)
        with_bi = lattice_segment("abc", lex,
                                  bigrams={("a", "bc"): 2.0}, beta=1.0)
        assert with_bi == ["a", "bc"]
        assert set("".join(uni)) == set("abc")

    def test_run_initial_transition(self):
        from deeplearning4j_tpu.nlp.cjk import lattice_segment
        lex = {"ab": -5.0, "c": -5.0, "a": -5.0, "bc": -5.0}
        out = lattice_segment("abc", lex,
                              bigrams={("<s>", "ab"): 2.0}, beta=1.0)
        assert out == ["ab", "c"]

    def test_beta_zero_equals_unigram(self):
        """beta=0 must reproduce the plain unigram lattice EXACTLY (both
        DP variants iterate the same _candidates arc set)."""
        from deeplearning4j_tpu.nlp.cjk import (JapaneseTokenizerFactory,
                                                _merge_kata_singles,
                                                lattice_segment)
        fac = JapaneseTokenizerFactory(bigram_beta=0.0)
        assert fac.bigrams is None
        for sent in ("私は学校に行きます", "研究生命科学", "ソフトウェアを使う",
                     "これはペンです", "東京タワーへ行った"):
            toks = fac.create(sent).get_tokens()
            expect = _merge_kata_singles(lattice_segment(
                sent, fac.lexicon, max_len=fac._max_word,
                run_candidates=True))
            assert toks == expect, (sent, toks, expect)

    def test_bigram_table_loaded(self):
        from deeplearning4j_tpu.nlp.lexicons import JAPANESE_BIGRAMS
        assert len(JAPANESE_BIGRAMS) > 10000
        assert all(v > 0 for v in JAPANESE_BIGRAMS.values())
        # span-initial rows exist
        assert any(k[0] == "<s>" for k in JAPANESE_BIGRAMS)

    def test_zh_fixture_size(self):
        import os
        base = os.path.join(os.path.dirname(__file__), "resources",
                            "cjk_gold_zh.txt")
        n = sum(1 for line in open(base, encoding="utf-8")
                if line.strip() and not line.startswith("#"))
        assert n >= 200                 # round-5 fixture power (r4 weak 4)
