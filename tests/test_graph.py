"""ComputationGraph: DAG construction, vertex ops, training, gradient checks
(reference test model: ``gradientcheck/GradientCheckTestsComputationGraph`` +
``nn/graph`` behavior tests).
"""
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu import (ComputationGraph, InputType,
                                NeuralNetConfiguration)
from deeplearning4j_tpu.nn.computation_graph import check_graph_gradients
from deeplearning4j_tpu.nn.conf.computation_graph import (
    ComputationGraphConfiguration, DuplicateToTimeSeriesVertex,
    ElementWiseVertex, L2NormalizeVertex, L2Vertex, LastTimeStepVertex,
    MergeVertex, PreprocessorVertex, ReshapeVertex, ScaleVertex, ShiftVertex,
    StackVertex, SubsetVertex, UnstackVertex)
from deeplearning4j_tpu.nn.conf.updaters import Adam, Sgd
from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.layers.recurrent import LSTM, RnnOutputLayer


def simple_graph(seed=42):
    conf = (NeuralNetConfiguration.builder()
            .seed(seed).updater(Adam(learning_rate=0.02))
            .graph_builder()
            .add_inputs("in")
            .add_layer("d0", DenseLayer(n_out=12, activation="tanh"), "in")
            .add_layer("d1", DenseLayer(n_out=12, activation="tanh"), "d0")
            .add_vertex("skip", ElementWiseVertex(op="add"), "d0", "d1")
            .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                          loss="mcxent"), "skip")
            .set_outputs("out")
            .set_input_types(InputType.feed_forward(4))
            .build())
    return ComputationGraph(conf).init()


def _toy(n=60, fin=4, classes=3, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, fin)).astype(np.float64)
    labels = rng.integers(0, classes, n)
    y = np.eye(classes)[labels]
    return x, y


def test_graph_fit_reduces_score():
    net = simple_graph()
    x, y = _toy()
    s0 = net.score(inputs=x, labels=y)
    net.fit(x, y, epochs=120)
    assert net.score(inputs=x, labels=y) < s0 * 0.5


def test_graph_gradient_check_skip_connection():
    net = simple_graph()
    x, y = _toy(n=12)
    assert check_graph_gradients(net, x, y)


def test_graph_multi_input_merge():
    conf = (NeuralNetConfiguration.builder()
            .seed(7).updater(Sgd(learning_rate=0.1))
            .graph_builder()
            .add_inputs("a", "b")
            .add_layer("da", DenseLayer(n_out=8, activation="tanh"), "a")
            .add_layer("db", DenseLayer(n_out=8, activation="tanh"), "b")
            .add_vertex("m", MergeVertex(), "da", "db")
            .add_layer("out", OutputLayer(n_out=2, activation="softmax",
                                          loss="mcxent"), "m")
            .set_outputs("out")
            .set_input_types(InputType.feed_forward(3),
                             InputType.feed_forward(5))
            .build())
    # merged feature size = 8 + 8
    assert conf.vertex_output_type("m").size == 16
    net = ComputationGraph(conf).init()
    rng = np.random.default_rng(1)
    xa = rng.standard_normal((10, 3))
    xb = rng.standard_normal((10, 5))
    y = np.eye(2)[rng.integers(0, 2, 10)]
    out = net.output(xa, xb)
    assert out.shape == (10, 2)
    assert check_graph_gradients(net, [xa, xb], y)


def test_graph_multi_output():
    conf = (NeuralNetConfiguration.builder()
            .seed(3).updater(Adam(learning_rate=0.05))
            .graph_builder()
            .add_inputs("in")
            .add_layer("trunk", DenseLayer(n_out=10, activation="relu"), "in")
            .add_layer("out1", OutputLayer(n_out=3, activation="softmax",
                                           loss="mcxent"), "trunk")
            .add_layer("out2", OutputLayer(n_out=1, activation="identity",
                                           loss="mse"), "trunk")
            .set_outputs("out1", "out2")
            .set_input_types(InputType.feed_forward(4))
            .build())
    net = ComputationGraph(conf).init()
    rng = np.random.default_rng(5)
    x = rng.standard_normal((20, 4))
    y1 = np.eye(3)[rng.integers(0, 3, 20)]
    y2 = rng.standard_normal((20, 1))
    s0 = net.score(inputs=[x], labels=[y1, y2])
    net.fit([x], [y1, y2], epochs=60)
    assert net.score(inputs=[x], labels=[y1, y2]) < s0
    o1, o2 = net.output(x)
    assert o1.shape == (20, 3) and o2.shape == (20, 1)
    assert check_graph_gradients(net, [x], [y1, y2])


def test_vertex_ops_numerics():
    """Scale/Shift/Subset/L2Normalize/Reshape/Stack/Unstack exact numerics."""
    b = (NeuralNetConfiguration.builder().seed(0).graph_builder()
         .add_inputs("in")
         .add_vertex("scale", ScaleVertex(scale_factor=2.0), "in")
         .add_vertex("shift", ShiftVertex(shift_factor=1.0), "scale")
         .add_vertex("sub", SubsetVertex(from_idx=1, to_idx=2), "shift")
         .add_vertex("norm", L2NormalizeVertex(), "sub")
         .add_layer("out", OutputLayer(n_out=2, activation="identity",
                                       loss="mse"), "norm")
         .set_outputs("out")
         .set_input_types(InputType.feed_forward(4)))
    net = ComputationGraph(b.build()).init()
    x = np.array([[1.0, 2.0, 3.0, 4.0]])
    acts = net.feed_forward(x)
    np.testing.assert_allclose(np.asarray(acts["scale"]), [[2, 4, 6, 8]])
    np.testing.assert_allclose(np.asarray(acts["shift"]), [[3, 5, 7, 9]])
    np.testing.assert_allclose(np.asarray(acts["sub"]), [[5, 7]])
    n = np.sqrt(25 + 49)
    np.testing.assert_allclose(np.asarray(acts["norm"]), [[5 / n, 7 / n]],
                               rtol=1e-6)


def test_stack_unstack_roundtrip():
    b = (NeuralNetConfiguration.builder().seed(0).graph_builder()
         .add_inputs("a", "b")
         .add_vertex("stack", StackVertex(), "a", "b")
         .add_layer("shared", DenseLayer(n_out=6, activation="tanh"), "stack")
         .add_vertex("ua", UnstackVertex(from_idx=0, stack_size=2), "shared")
         .add_vertex("ub", UnstackVertex(from_idx=1, stack_size=2), "shared")
         .add_vertex("l2", L2Vertex(), "ua", "ub")
         .add_layer("out", OutputLayer(n_out=1, activation="sigmoid",
                                       loss="xent"), "l2")
         .set_outputs("out")
         .set_input_types(InputType.feed_forward(4),
                          InputType.feed_forward(4)))
    net = ComputationGraph(b.build()).init()
    rng = np.random.default_rng(9)
    xa = rng.standard_normal((6, 4))
    xb = rng.standard_normal((6, 4))
    acts = net.feed_forward(xa, xb)
    assert acts["stack"].shape == (12, 4)
    assert acts["ua"].shape == (6, 6) and acts["ub"].shape == (6, 6)
    # siamese distance: same input pair → zero-ish distance (eps floor)
    acts_same = net.feed_forward(xa, xa)
    assert float(np.max(np.asarray(acts_same["l2"]))) < 1e-3
    y = np.eye(2)[rng.integers(0, 2, 6)][:, :1]
    assert check_graph_gradients(net, [xa, xb], y)


def test_seq2seq_vertices():
    """Encoder→LastTimeStep→DuplicateToTimeSeries→decoder (reference
    rnn vertex pattern for seq2seq)."""
    T = 5
    b = (NeuralNetConfiguration.builder().seed(11)
         .updater(Adam(learning_rate=0.02)).graph_builder()
         .add_inputs("seq")
         .add_layer("enc", LSTM(n_out=8, activation="tanh"), "seq")
         .add_vertex("last", LastTimeStepVertex(mask_input="seq"), "enc")
         .add_vertex("dup", DuplicateToTimeSeriesVertex(ts_input="seq"),
                     "last", "seq")
         .add_layer("dec", LSTM(n_out=8, activation="tanh"), "dup")
         .add_layer("out", RnnOutputLayer(n_out=3, activation="softmax",
                                          loss="mcxent"), "dec")
         .set_outputs("out")
         .set_input_types(InputType.recurrent(4, T)))
    net = ComputationGraph(b.build()).init()
    rng = np.random.default_rng(2)
    x = rng.standard_normal((4, T, 4))
    y = np.eye(3)[rng.integers(0, 3, (4, T))]
    out = net.output(x)
    assert out.shape == (4, T, 3)
    assert check_graph_gradients(net, x, y, subset=40)
    # masked: last vertex picks last unmasked step
    mask = np.ones((4, T)); mask[0, 3:] = 0
    acts_m = net.feed_forward(x)  # unmasked reference
    s0 = net.score(inputs=[x], labels=[y])
    net.fit([x], [y], masks=[mask], epochs=3)  # trains without error
    assert np.isfinite(net.get_score())


def test_graph_json_roundtrip():
    net = simple_graph()
    js = net.conf.to_json()
    conf2 = ComputationGraphConfiguration.from_json(js)
    assert conf2.topological_order == net.conf.topological_order
    assert set(conf2.vertices) == set(net.conf.vertices)
    net2 = ComputationGraph(conf2).init()
    x, y = _toy(n=8)
    # same seed → same init → same outputs
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               np.asarray(net2.output(x)), rtol=1e-6)


def test_graph_evaluate():
    net = simple_graph()
    x, y = _toy(n=90)
    net.fit(x, y, epochs=150)
    ev = net.evaluate(x, y)
    assert ev.accuracy() > 0.7


def test_cycle_detection():
    from deeplearning4j_tpu.nn.conf.computation_graph import GraphBuilder
    b = (GraphBuilder()
         .add_inputs("in")
         .add_vertex("a", ScaleVertex(scale_factor=1.0), "b")
         .add_vertex("b", ScaleVertex(scale_factor=1.0), "a")
         .set_outputs("b"))
    with pytest.raises(ValueError, match="cycle"):
        b.build()


def test_graph_builder_modules():
    """Reusable sub-graph blocks (reference GraphBuilderModule)."""
    import numpy as np
    from deeplearning4j_tpu.nn.conf.modules import (ConvBnBlock,
                                                    InceptionBlock,
                                                    ResidualBlock)
    from deeplearning4j_tpu.nn.conf.computation_graph import GraphBuilder
    from deeplearning4j_tpu.nn.conf.input_type import InputType
    from deeplearning4j_tpu.nn.conf.updaters import Sgd
    from deeplearning4j_tpu.nn.layers.feedforward import OutputLayer
    from deeplearning4j_tpu.nn.layers.pooling import GlobalPoolingLayer
    from deeplearning4j_tpu.nn.computation_graph import ComputationGraph

    g = GraphBuilder({"updater": Sgd(learning_rate=0.1)})
    g.add_inputs("in").set_input_types(InputType.convolutional(16, 16, 3))
    x = ConvBnBlock(8, (3, 3)).add_layers(g, "stem", "in")
    x = ResidualBlock((4, 4, 8), project=True).add_layers(g, "res", x)
    x = InceptionBlock(4, 2, 4, 2, 4, 4).add_layers(g, "inc", x)
    g.add_layer("gap", GlobalPoolingLayer(pooling_type="avg"), x)
    g.add_layer("out", OutputLayer(n_out=5, activation="softmax",
                                   loss="mcxent"), "gap")
    g.set_outputs("out")
    net = ComputationGraph(g.build()).init()
    rng = np.random.default_rng(0)
    xs = rng.standard_normal((2, 16, 16, 3)).astype(np.float32)
    ys = np.eye(5, dtype=np.float32)[rng.integers(0, 5, 2)]
    net.fit([xs], [ys])
    out = net.output(xs)
    out = out[0] if isinstance(out, (list, tuple)) else out
    assert np.asarray(out).shape == (2, 5)


def test_graph_evaluate_variants():
    """CG evaluate/evaluate_regression/evaluate_roc parity with MLN."""
    import numpy as np
    from deeplearning4j_tpu.nn.conf.computation_graph import GraphBuilder
    from deeplearning4j_tpu.nn.conf.input_type import InputType
    from deeplearning4j_tpu.nn.conf.updaters import Adam
    from deeplearning4j_tpu.nn.layers.feedforward import (DenseLayer,
                                                          OutputLayer)
    from deeplearning4j_tpu.nn.computation_graph import ComputationGraph

    g = GraphBuilder({"updater": Adam(learning_rate=0.05)})
    g.add_inputs("in").set_input_types(InputType.feed_forward(4))
    g.add_layer("h", DenseLayer(n_out=12, activation="relu"), "in")
    g.add_layer("out", OutputLayer(n_out=2, activation="softmax",
                                   loss="mcxent"), "h")
    g.set_outputs("out")
    net = ComputationGraph(g.build()).init()
    rng = np.random.default_rng(0)
    y_cls = rng.integers(0, 2, 80)
    x = rng.standard_normal((80, 4)).astype(np.float32)
    x[:, 0] += y_cls * 2.5
    y = np.eye(2, dtype=np.float32)[y_cls]
    for _ in range(40):
        net.fit([x], [y])
    assert net.evaluate(x, y).accuracy() > 0.9
    roc = net.evaluate_roc(x, y)
    assert roc.calculate_auc() > 0.9
    reg = net.evaluate_regression(x, y)
    assert reg.average_mean_squared_error() < 0.2


def test_graph_bf16_and_remat():
    """CG under compute_dtype bfloat16 + cache_mode remat: trains, masters
    stay f32 (mixed precision plumbing on the graph path)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from deeplearning4j_tpu.nn.conf.computation_graph import GraphBuilder
    from deeplearning4j_tpu.nn.conf.input_type import InputType
    from deeplearning4j_tpu.nn.conf.updaters import Adam
    from deeplearning4j_tpu.nn.layers.feedforward import (DenseLayer,
                                                          OutputLayer)
    from deeplearning4j_tpu.nn.computation_graph import ComputationGraph
    g = GraphBuilder({"updater": Adam(learning_rate=0.05),
                      "compute_dtype": "bfloat16", "cache_mode": "remat"})
    g.add_inputs("in").set_input_types(InputType.feed_forward(4))
    g.add_layer("h", DenseLayer(n_out=8, activation="relu"), "in")
    g.add_layer("out", OutputLayer(n_out=2, activation="softmax",
                                   loss="mcxent"), "h")
    g.set_outputs("out")
    net = ComputationGraph(g.build()).init()
    rng = np.random.default_rng(0)
    x = rng.standard_normal((16, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 16)]
    s0 = None
    for _ in range(15):
        net.fit([x], [y])
        if s0 is None:
            s0 = net.get_score()
    assert net.get_score() < s0
    for leaf in jax.tree_util.tree_leaves(net.params):
        assert leaf.dtype == jnp.float32


def test_graph_fit_on_device():
    """ComputationGraph.fit_on_device: scanned epochs train a two-input
    graph and match bookkeeping."""
    import jax
    conf = (NeuralNetConfiguration.builder()
            .seed(5).updater(Adam(learning_rate=0.05))
            .graph_builder()
            .add_inputs("a", "b")
            .set_input_types(InputType.feed_forward(3),
                             InputType.feed_forward(2))
            .add_layer("da", DenseLayer(n_out=8, activation="tanh"), "a")
            .add_layer("db", DenseLayer(n_out=8, activation="tanh"), "b")
            .add_vertex("merge", MergeVertex(), "da", "db")
            .add_layer("out", OutputLayer(n_out=2, activation="softmax",
                                          loss="mcxent"), "merge")
            .set_outputs("out")
            .build())
    net = ComputationGraph(conf).init()
    rng = np.random.default_rng(0)
    n = 100
    a = rng.standard_normal((n, 3)).astype(np.float32)
    b = rng.standard_normal((n, 2)).astype(np.float32)
    # label depends on both inputs -> must use both branches to learn
    y = np.eye(2, dtype=np.float32)[((a[:, 0] + b[:, 0]) > 0).astype(int)]
    net.fit_on_device([a, b], [y], batch_size=32, epochs=40)
    assert net.epoch == 40
    assert net.iteration == 40 * (100 // 32 + 1)  # 3 scanned + 1 tail
    preds = np.asarray(net.output_single(a, b))
    acc = (preds.argmax(1) == y.argmax(1)).mean()
    assert acc > 0.85, acc
