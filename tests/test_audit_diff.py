"""graftaudit differential gate (ISSUE 16): the lifetime/donation
solver rules (AX007–AX010) and the ``--diff-cards`` budget gate.

Three layers:

* **rule units** — AX007's exact donation set (donatable positive,
  aliased-shape-mismatch negative, live-after-call veto), AX008's
  peak-live ceiling, AX009's scalar-variant churn, AX010's card drift.
* **the injected-regression suite** — the four classic silent IR
  regressions are synthetically introduced (an f64 escape, a dropped
  donation, a grown collective, a new ``pure_callback``) and each MUST
  fail the gate with the rule that names the bug; a stale budget entry
  MUST exit 2.  A gate that cannot fail is decoration.
* **the tier-1 gate** — ``--diff-cards`` semantics over the real
  canonical set against the committed ``budgets.json`` + ``cards/``:
  green on the tier-1 rig, every program budgeted, nothing skipped
  silently.
"""
import dataclasses
import json
import sys
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp

REPO_ROOT = Path(__file__).resolve().parents[1]
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))

from tools.graftaudit import (AUDIT_RULES, AuditConfig,  # noqa: E402
                              AuditProgram, analyze_program,
                              audit_programs, write_cards)
from tools.graftaudit.canonical import (BUDGETS_PATH,  # noqa: E402
                                        CANONICAL_CONFIG, CARDS_DIR,
                                        build_canonical)
from tools.graftaudit.cli import main as audit_cli  # noqa: E402
from tools.graftaudit.diff import (budget_entry,  # noqa: E402
                                   check_budgets, load_budgets)

from deeplearning4j_tpu.nn.compile_cache import InstrumentedJit  # noqa: E402

FAST = AuditConfig(compile="never", min_donate_bytes=256)


def prog(fun, *args, name="train_step", donate=(), **kw) -> AuditProgram:
    entry = InstrumentedJit(fun, name=name, donate_argnums=donate)
    entry(*args)
    specs = entry.audit_specs()
    assert specs, "trace-time capture should have recorded the spec"
    return AuditProgram(name=name, entry=entry, spec=specs[-1], **kw)


def run_rule(code, p, config=FAST):
    return AUDIT_RULES[code](analyze_program(p, config))


# ------------------------------------------------------------- AX007 units
class TestAX007ExactSet:
    def test_dead_arg_with_aliasable_output_fires(self):
        # params is big, observed dead (the fixture drops its only
        # binding), and the output aliases its shape/dtype exactly —
        # the maximal set must contain it, the declaration doesn't
        def fn(params, x):
            return params * 0.9 + jnp.sum(x)

        fs = run_rule("AX007", prog(fn, jnp.ones((64, 64), jnp.float32),
                                    jnp.ones((8,), jnp.float32)))
        assert len(fs) == 1 and "arg 0" in fs[0].message
        assert "maximal safe donation set" in fs[0].message

    def test_declared_donation_is_silent(self):
        def fn(params, x):
            return params * 0.9 + jnp.sum(x)

        p = prog(fn, jnp.ones((64, 64), jnp.float32),
                 jnp.ones((8,), jnp.float32), donate=(0,))
        assert run_rule("AX007", p) == []

    def test_no_aliasable_output_is_silent(self):
        # every arg is dead but the program only returns a scalar:
        # donation buys nothing (no shape/dtype-compatible output
        # leaf), and unlike AX005's heuristic the solver must stay quiet
        def fn(params, state, x):
            return jnp.sum(params) + jnp.sum(state) + jnp.sum(x)

        args = (jnp.ones((64, 64), jnp.float32),
                jnp.ones((8,), jnp.float32),
                jnp.ones((64, 64), jnp.float32))
        assert run_rule("AX007", prog(fn, *args)) == []
        # ... while AX005's kind-contract threshold heuristic DOES cry
        # wolf on serve's dead batch (arg 2) — exactly the imprecision
        # AX007 supersedes
        assert run_rule("AX005", prog(fn, *args, name="serve")) != []

    def test_observed_live_arg_vetoes_the_contract(self):
        # the caller demonstrably still holds the binding, so even
        # though the train_step contract says arg 0 is dead after the
        # call, the observation wins and AX007 must not fire
        def fn(params, x):
            return params * 0.9 + jnp.sum(x)

        held = jnp.ones((64, 64), jnp.float32)
        entry = InstrumentedJit(fn, name="train_step", donate_argnums=())
        entry(held, jnp.ones((8,), jnp.float32))
        p = AuditProgram(name="train_step", entry=entry,
                         spec=entry.audit_specs()[-1])
        ir_prog = analyze_program(p, FAST)
        assert ir_prog.lifetime.args[0].caller == "live"
        assert AUDIT_RULES["AX007"](ir_prog) == []
        del held

    def test_below_threshold_is_silent(self):
        def fn(params, x):
            return params * 0.9 + jnp.sum(x)

        cfg = AuditConfig(compile="never", min_donate_bytes=1 << 30)
        fs = run_rule("AX007", prog(fn, jnp.ones((64, 64), jnp.float32),
                                    jnp.ones((8,), jnp.float32)), cfg)
        assert fs == []


# ------------------------------------------------------- AX008/AX009/AX010
class TestAX008PeakLive:
    def test_over_ceiling_fires_and_under_is_silent(self):
        def fn(x):
            return x @ x + x

        tight = AuditConfig(compile="never",
                            peak_live_budgets={"train_step": 1})
        fs = run_rule("AX008", prog(fn, jnp.ones((16, 16))), tight)
        assert len(fs) == 1 and "peak-live-bytes" in fs[0].message
        roomy = AuditConfig(compile="never",
                            peak_live_budgets={"train_step": 1 << 30})
        assert run_rule("AX008", prog(fn, jnp.ones((16, 16))), roomy) == []

    def test_unbudgeted_program_is_silent(self):
        def fn(x):
            return x @ x

        cfg = AuditConfig(compile="never",
                          peak_live_budgets={"some_other_program": 1})
        assert run_rule("AX008", prog(fn, jnp.ones((16, 16))), cfg) == []


class TestAX009VariantChurn:
    def test_python_scalar_value_churn_fires(self):
        # capture "all" (the canonical-gate mode): each raw-scalar value
        # lands its own spec in the audit ring, all collapsing onto one
        # program once the value is erased — the churn AX009 names
        from deeplearning4j_tpu.nn.compile_cache import (
            audit_capture_mode, set_audit_capture)

        prev = audit_capture_mode()
        set_audit_capture("all")
        try:
            entry = InstrumentedJit(lambda x, t: x * t, name="decode")
            entry(jnp.ones((4,)), 0.7)
            entry(jnp.ones((4,)), 0.9)
        finally:
            set_audit_capture(prev)
        assert len(entry.audit_specs()) == 2
        p = AuditProgram(name="decode", entry=entry,
                         spec=entry.audit_specs()[-1])
        fs = AUDIT_RULES["AX009"](analyze_program(p, FAST))
        assert len(fs) == 1 and "2 captured call specs" in fs[0].message

    def test_committed_scalar_is_one_variant(self):
        from deeplearning4j_tpu.nn.compile_cache import (
            audit_capture_mode, set_audit_capture)

        prev = audit_capture_mode()
        set_audit_capture("all")
        try:
            entry = InstrumentedJit(lambda x, t: x * t, name="decode")
            entry(jnp.ones((4,)), np.float32(0.7))
            entry(jnp.ones((4,)), np.float32(0.9))   # same committed spec
        finally:
            set_audit_capture(prev)
        assert len(entry.audit_specs()) == 1
        p = AuditProgram(name="decode", entry=entry,
                         spec=entry.audit_specs()[-1])
        assert AUDIT_RULES["AX009"](analyze_program(p, FAST)) == []


class TestAX010CardDrift:
    def _ir(self, tmp_path, name="gate_probe"):
        def fn(x):
            return x * 2

        p = prog(fn, jnp.ones((4,)), name=name)
        cfg = AuditConfig(compile="never", cards_dir=str(tmp_path))
        return analyze_program(p, cfg)

    def test_missing_card_fires(self, tmp_path):
        fs = AUDIT_RULES["AX010"](self._ir(tmp_path))
        assert len(fs) == 1 and "no committed card" in fs[0].message

    def test_matching_card_is_silent_and_drift_fires(self, tmp_path):
        ir_prog = self._ir(tmp_path)
        [path] = write_cards([ir_prog], str(tmp_path))
        assert AUDIT_RULES["AX010"](ir_prog) == []
        card = json.loads(Path(path).read_text())
        card["donation"]["declared"] = [0]          # stable-field edit
        Path(path).write_text(json.dumps(card))
        fs = AUDIT_RULES["AX010"](ir_prog)
        assert len(fs) == 1 and "'donation' drifted" in fs[0].message

    def test_unarmed_config_is_silent(self):
        def fn(x):
            return x * 2

        assert AUDIT_RULES["AX010"](
            analyze_program(prog(fn, jnp.ones((4,))), FAST)) == []


# ------------------------------------------------- injected regressions
# Each of the four classic silent IR regressions is synthetically
# introduced and MUST produce the finding the gate exits 1 on, with the
# rule code that names the bug (the cli returns 1 on any finding).
class TestInjectedRegressions:
    def test_injected_f64_escape_fails_as_ax001(self):
        if not jax.config.jax_enable_x64:
            pytest.skip("needs x64 for a dtype-defaulted f64")

        def fn(x):
            return jnp.sum(x) + jnp.zeros(())    # injected f64 join

        res = audit_programs([prog(fn, jnp.ones((4,), jnp.float32))],
                             [], FAST)
        assert [f.rule for f in res.findings] == ["AX001"]

    def test_injected_dropped_donation_fails_as_ax007(self):
        # the program's reviewed budget row says arg 0 is donated;
        # the fresh build dropped it — donation_min catches it even if
        # the caller-side liveness probe sees nothing
        def fn(params, x):
            return params * 0.9 + jnp.sum(x)

        ir_prog = analyze_program(
            prog(fn, jnp.ones((64, 64), jnp.float32),
                 jnp.ones((8,), jnp.float32), donate=(0,)), FAST)
        row = budget_entry(ir_prog)
        assert row["donation_min"] == [0]
        dropped = dataclasses.replace(ir_prog, donate=())
        findings, stale = check_budgets(
            [dropped], {"programs": {ir_prog.name: row}})
        assert stale == []
        assert [f.rule for f in findings] == ["AX007"]
        assert "budgeted donation dropped" in findings[0].message

    def test_injected_grown_collective_fails_as_ax008(self):
        # a census 2x over the reviewed ceiling (the grown-all-reduce
        # shape of a lost reduce-scatter) breaches collective_bytes
        def fn(x):
            return x * 2

        ir_prog = analyze_program(prog(fn, jnp.ones((4,))), FAST)
        grown = dataclasses.replace(
            ir_prog,
            census={"all-reduce": {"count": 12, "bytes": 9000}})
        findings, _ = check_budgets(
            [grown], {"programs": {ir_prog.name: {
                "collective_bytes": 4500, "collective_count": 11}}})
        assert sorted(f.rule for f in findings) == ["AX008", "AX008"]
        assert any("collective bytes 9000" in f.message for f in findings)
        assert any("collective count 12" in f.message for f in findings)

    def test_injected_callback_fails_as_ax004_and_breaches_budget(self):
        def fn(x):
            y = jax.pure_callback(
                lambda v: np.asarray(v) * 2,
                jax.ShapeDtypeStruct(x.shape, x.dtype), x)
            return y + 1

        p = prog(fn, jnp.ones((4,), jnp.float32))
        res = audit_programs([p], [], FAST)
        assert "AX004" in [f.rule for f in res.findings]
        # and the budget's callback ceiling fails closed independently
        ir_prog = analyze_program(p, FAST)
        findings, _ = check_budgets(
            [ir_prog], {"programs": {p.name: {"callbacks": 0}}})
        assert [f.rule for f in findings] == ["AX008"]
        assert "host callback eqns" in findings[0].message

    def test_stale_budget_entry_is_exit2_class(self):
        # a budgeted program that no longer exists (and is not an
        # explicit host skip) must surface as stale, never be ignored
        findings, stale = check_budgets(
            [], {"programs": {"ghost_program": {"callbacks": 0}}})
        assert findings == [] and stale == ["ghost_program"]
        # ... unless the host explicitly could not build it
        findings, stale = check_budgets(
            [], {"programs": {"ghost_program": {"callbacks": 0}}},
            skipped={"ghost_program": "needs 8 devices"})
        assert findings == [] and stale == []

    def test_budgets_file_must_exist_and_parse(self, tmp_path):
        with pytest.raises(OSError):
            load_budgets(str(tmp_path / "nope.json"))
        bad = tmp_path / "bad.json"
        bad.write_text("{}")
        with pytest.raises(ValueError):
            load_budgets(str(bad))


# ------------------------------------------------------- the tier-1 gate
@pytest.fixture(scope="module")
def gate():
    """ONE full gate pipeline run shared by the gate tests: canonical
    build, audit under CANONICAL_CONFIG (AX008 ceilings + AX010 card
    drift armed), budget checks against the committed budgets.json."""
    cs = build_canonical()
    result = audit_programs(cs.programs, cs.suppressions,
                            CANONICAL_CONFIG)
    budgets = load_budgets(str(BUDGETS_PATH))
    findings, stale = check_budgets(result.irs, budgets, cs.skipped)
    return cs, result, budgets, findings, stale


def test_diff_gate_is_green_on_the_tier1_rig(gate):
    """THE gate: the committed budgets + cards describe the canonical
    set as built — zero findings, zero stale rows, and coverage is
    EXPLICIT: the tier-1 rig builds every program (skipped must be
    empty, so a quietly-unbuildable program can never fake green)."""
    cs, result, budgets, findings, stale = gate
    assert cs.skipped == {}, cs.skipped
    assert result.findings == [], \
        "\n".join(f.format() for f in result.findings)
    assert result.stale_suppressions == []
    assert findings == [], "\n".join(f.format() for f in findings)
    assert stale == []
    # every canonical program is budgeted — no unguarded program rides
    # along, and no budget row outlives its program
    assert set(budgets["programs"]) == {ir.name for ir in result.irs}


def test_sweep_acceptance_no_undeclared_donatable_args(gate):
    """ISSUE 16 acceptance: after the donation sweep, the solver's
    maximal safe donation set matches the declaration on every
    canonical train program — AX007 has nothing left to say there (the
    CPU-only serve/prefill/decode skips are justified manifest
    suppressions, pinned in test_audit.py)."""
    _, result, _, _, _ = gate
    for ir_prog in result.irs:
        if not ir_prog.kind.startswith(("train_step", "pretrain")):
            continue
        assert ir_prog.lifetime is not None, ir_prog.name
        undeclared = [a for a in ir_prog.lifetime.maximal_donation
                      if a not in ir_prog.donate]
        assert undeclared == [], \
            f"{ir_prog.name}: solver says donate {undeclared} too"


def test_every_budget_row_is_ratchet_tight(gate):
    """The committed ceilings actually bite: each exact metric
    (collective bytes/count, callbacks, dtype histogram) equals the
    current value — the ratchet has zero slack to absorb a regression —
    and the jittery metrics (temp, peak-live) carry only their
    documented headroom."""
    _, result, budgets, _, _ = gate
    for ir_prog in result.irs:
        row = budgets["programs"][ir_prog.name]
        fresh = budget_entry(ir_prog)
        for k in ("collective_bytes", "collective_count", "callbacks",
                  "dtypes", "donation_min"):
            assert row[k] == fresh[k], (ir_prog.name, k)


def test_cli_diff_gate_exit_codes(gate, tmp_path, capsys):
    """End-to-end exit-code wiring on a one-program subset (cheap):
    0 = clean against the committed artifacts, 1 = a ceiling breach,
    2 = a stale budget entry; a missing budgets file refuses to run."""
    assert audit_cli(["--diff-cards", "--programs", "serve"]) == 0

    budgets = json.loads(Path(BUDGETS_PATH).read_text())
    breach = {"programs": {"serve": dict(budgets["programs"]["serve"],
                                         temp_bytes=0)}}
    bpath = tmp_path / "budgets.json"
    bpath.write_text(json.dumps(breach))
    assert audit_cli(["--diff-cards", "--programs", "serve",
                      "--budgets", str(bpath)]) == 1
    out = capsys.readouterr().out
    assert "AX008" in out and "XLA temp bytes" in out

    stale = {"programs": {"serve": budgets["programs"]["serve"],
                          "ghost_program": {"callbacks": 0}}}
    bpath.write_text(json.dumps(stale))
    assert audit_cli(["--diff-cards", "--programs", "serve,ghost",
                      "--budgets", str(bpath)]) == 2

    assert audit_cli(["--diff-cards", "--programs", "serve",
                      "--budgets", str(tmp_path / "missing.json")]) == 2
