"""Stats/UI pipeline tests (reference test model: ``deeplearning4j-core``
``ui/`` tests posting into ``InMemoryStatsStorage`` — no browser needed)."""
import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.data.mnist import IrisDataSetIterator
from deeplearning4j_tpu.nn.conf.input_type import InputType
from deeplearning4j_tpu.nn.conf.multi_layer import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.updaters import Adam
from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.ui import (FileStatsStorage, InMemoryStatsStorage,
                                   RemoteUIStatsStorageRouter, StatsListener,
                                   StatsReport, UIServer, array_stats)


def _train_with(storage, epochs=3, session_id="s1"):
    conf = (NeuralNetConfiguration.builder()
            .seed(7).activation("tanh").weight_init("xavier")
            .updater(Adam(learning_rate=0.02))
            .list()
            .layer(DenseLayer(n_out=8))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    net = MultiLayerNetwork(conf).init()
    net.set_listeners(StatsListener(storage, session_id=session_id))
    it = IrisDataSetIterator(batch_size=50)
    for _ in range(epochs):
        it.reset()
        net.fit(it)
    return net


def test_array_stats_matches_numpy():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((40, 7)).astype(np.float32)
    s = array_stats(x)
    assert s["mean"] == pytest.approx(float(x.mean()), abs=1e-5)
    assert s["std"] == pytest.approx(float(x.std()), abs=1e-5)
    assert s["norm2"] == pytest.approx(float(np.linalg.norm(x)), rel=1e-5)
    assert sum(s["hist"]) == x.size
    assert len(s["hist"]) == 20


def test_stats_listener_collects():
    storage = InMemoryStatsStorage()
    _train_with(storage)
    assert storage.list_session_ids() == ["s1"]
    recs = storage.get_records("s1")
    assert len(recs) == 9  # 3 epochs x 3 batches of 50
    r = recs[-1]
    assert np.isfinite(r.score)
    assert "layer_0/W" in r.param_stats
    assert "layer_0/W" in r.update_stats  # deltas from 2nd record on
    # params actually moved
    assert r.update_stats["layer_0/W"]["norm2"] > 0


def test_file_storage_roundtrip(tmp_path):
    path = str(tmp_path / "stats.bin")
    storage = FileStatsStorage(path)
    _train_with(storage, epochs=2, session_id="file_sess")
    storage.close()
    reopened = FileStatsStorage(path)
    recs = reopened.get_records("file_sess")
    assert len(recs) == 6
    assert recs[0].param_stats["layer_0/W"]["hist"]
    reopened.close()


def test_ui_server_endpoints():
    storage = InMemoryStatsStorage()
    server = UIServer(port=0).start()
    server.attach(storage)
    try:
        _train_with(storage, epochs=2, session_id="ui_sess")
        base = f"http://127.0.0.1:{server.port}"
        sessions = json.load(urllib.request.urlopen(f"{base}/train/sessions"))
        assert sessions == ["ui_sess"]
        o = json.load(urllib.request.urlopen(f"{base}/train/ui_sess/overview"))
        assert len(o["scores"]) == 6
        assert "layer_0/W" in o["param_norms"]
        m = json.load(urllib.request.urlopen(f"{base}/train/ui_sess/model"))
        assert m["iteration"] == o["iterations"][-1]
        html = urllib.request.urlopen(base).read().decode()
        assert "dl4j-tpu training" in html
    finally:
        server.stop()


def test_remote_router_posts_to_server():
    server = UIServer(port=0).start()
    try:
        router = RemoteUIStatsStorageRouter(f"http://127.0.0.1:{server.port}")
        report = StatsReport(session_id="remote_s", worker_id="w0",
                             iteration=1, epoch=0, timestamp=0.0, score=1.5,
                             iter_time_ms=10.0)
        router.put_record(report)
        recs = server.storage.get_records("remote_s")
        assert len(recs) == 1 and recs[0].score == 1.5
    finally:
        server.stop()


def test_remote_rejects_malformed():
    server = UIServer(port=0).start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/remote", data=b"not json",
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req)
        assert ei.value.code == 400
    finally:
        server.stop()


def test_profiler_listener_and_memory_stats(tmp_path):
    """ProfilerListener brackets an iteration window with an XLA trace;
    device_memory_stats degrades to None on backends without HBM stats."""
    from deeplearning4j_tpu.utils.profiling import (ProfilerListener,
                                                    device_memory_stats,
                                                    trace_annotation)
    import numpy as np
    from deeplearning4j_tpu.nn.conf.multi_layer import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.input_type import InputType
    from deeplearning4j_tpu.nn.conf.updaters import Sgd
    from deeplearning4j_tpu.nn.layers.feedforward import (DenseLayer,
                                                          OutputLayer)
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    conf = (NeuralNetConfiguration.builder().seed(1)
            .updater(Sgd(learning_rate=0.1)).list()
            .layer(DenseLayer(n_out=8, activation="relu"))
            .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(3)).build())
    net = MultiLayerNetwork(conf).init()
    lst = ProfilerListener(str(tmp_path / "trace"), start_iteration=2,
                           num_iterations=2)
    net.set_listeners(lst)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((20, 3)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 20)]
    with trace_annotation("fit"):
        for _ in range(6):
            net.fit(x, y)
    assert lst.captured and not lst._active
    assert any((tmp_path / "trace").rglob("*"))  # trace files exist
    stats = device_memory_stats()
    assert stats is None or "bytes_in_use" in stats


def test_ui_components_render(tmp_path):
    """Chart/table/text DSL -> standalone HTML (reference ui-components)."""
    from deeplearning4j_tpu.ui import (ChartHistogram, ChartLine,
                                       ChartScatter, ComponentTable,
                                       ComponentText, render_page)
    import numpy as np
    line = (ChartLine("loss").add_series("train", [0, 1, 2], [3.0, 2.0, 1.0])
            .add_series("val", [0, 1, 2], [3.5, 2.5, 2.0]))
    scat = ChartScatter("embed").add_series("pts", [0.1, 0.5], [0.2, 0.9])
    hist = ChartHistogram.of(np.random.default_rng(0).standard_normal(500),
                             n_bins=10, title="weights")
    table = ComponentTable(["metric", "value"], [["acc", 0.98],
                                                ["f1", 0.97]], title="eval")
    page = render_page([ComponentText("Training report", bold=True),
                        line, scat, hist, table])
    assert page.startswith("<!DOCTYPE html>")
    assert page.count("<svg") == 3 and "<table" in page
    assert "polyline" in page and "circle" in page and "rect" in page
    assert "acc" in page and "weights" in page
    (tmp_path / "report.html").write_text(page)


def test_torch_interop_roundtrip():
    """torch DataLoader -> our iterator -> train; and back to torch."""
    import numpy as np
    torch = pytest.importorskip("torch")
    import torch.utils.data as tud
    from deeplearning4j_tpu.data import (INDArrayDataSetIterator,
                                         as_torch_dataset, from_torch)
    rng = np.random.default_rng(0)
    y_cls = rng.integers(0, 3, 60)
    x = rng.standard_normal((60, 4)).astype(np.float32)
    x[:, :3] += np.eye(3, dtype=np.float32)[y_cls] * 2
    tds = tud.TensorDataset(torch.from_numpy(x), torch.from_numpy(y_cls))
    it = from_torch(tds, batch_size=20, n_classes=3)
    batches = list(it)
    assert len(batches) == 3
    assert batches[0].labels.shape == (20, 3)

    from deeplearning4j_tpu.nn.conf.input_type import InputType
    from deeplearning4j_tpu.nn.conf.multi_layer import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.updaters import Adam
    from deeplearning4j_tpu.nn.layers.feedforward import (DenseLayer,
                                                          OutputLayer)
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    conf = (NeuralNetConfiguration.builder().seed(2)
            .updater(Adam(learning_rate=0.05)).list()
            .layer(DenseLayer(n_out=12, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4)).build())
    net = MultiLayerNetwork(conf).init()
    net.fit(it, epochs=25)
    assert net.evaluate(x, np.eye(3, dtype=np.float32)[y_cls]).accuracy() > 0.9
    # NCHW image batches transpose to NHWC
    imgs = torch.zeros(4, 3, 8, 8)
    t2 = tud.TensorDataset(imgs, torch.zeros(4, dtype=torch.long))
    b = next(iter(from_torch(t2, batch_size=4, n_classes=2)))
    assert b.features.shape == (4, 8, 8, 3)
    # reverse direction
    back = as_torch_dataset(INDArrayDataSetIterator(
        x, np.eye(3, dtype=np.float32)[y_cls], batch_size=30))
    got = list(iter(back))
    assert len(got) == 2 and got[0][0].shape == (30, 4)


def test_convolutional_iteration_listener(tmp_path):
    """Activation grids rendered to HTML during training (reference
    RemoteConvolutionalIterationListener role)."""
    import numpy as np
    from deeplearning4j_tpu.nn.conf.input_type import InputType
    from deeplearning4j_tpu.nn.conf.multi_layer import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.updaters import Sgd
    from deeplearning4j_tpu.nn.layers.convolution import ConvolutionLayer
    from deeplearning4j_tpu.nn.layers.feedforward import OutputLayer
    from deeplearning4j_tpu.nn.layers.pooling import GlobalPoolingLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.train.listeners import \
        ConvolutionalIterationListener
    conf = (NeuralNetConfiguration.builder().seed(0)
            .updater(Sgd(learning_rate=0.05)).list()
            .layer(ConvolutionLayer(n_out=4, kernel_size=(3, 3),
                                    activation="relu",
                                    convolution_mode="same"))
            .layer(GlobalPoolingLayer(pooling_type="avg"))
            .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.convolutional(8, 8, 1)).build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    x = rng.standard_normal((6, 8, 8, 1)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 6)]
    lst = ConvolutionalIterationListener(x[:1], frequency=2,
                                         output_dir=str(tmp_path))
    net.set_listeners(lst)
    for _ in range(4):
        net.fit(x, y)
    files = list(tmp_path.glob("activations_*.html"))
    assert len(files) == 2
    content = files[0].read_text()
    assert "<svg" in content and "rect" in content


def test_tsne_module_upload_and_coords():
    """/tsne endpoints (reference ui/module/tsne/TsneModule.java)."""
    from deeplearning4j_tpu.ui import upload_tsne, coords_to_csv_lines
    server = UIServer(port=0).start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        coords = np.array([[0.0, 1.0], [2.0, 3.0], [4.0, 5.0]])
        upload_tsne(base, coords, labels=["a", "b", "c"])
        sessions = json.load(urllib.request.urlopen(f"{base}/tsne/sessions"))
        assert sessions == ["UploadedFile"]
        lines = json.load(
            urllib.request.urlopen(f"{base}/tsne/coords/UploadedFile"))
        assert lines == coords_to_csv_lines(coords, ["a", "b", "c"])
        assert lines[0] == "0,1,a"
        # explicit session id
        upload_tsne(base, coords[:2], session_id="run7")
        sessions = json.load(urllib.request.urlopen(f"{base}/tsne/sessions"))
        assert "run7" in sessions
        html = urllib.request.urlopen(f"{base}/tsne").read().decode()
        assert "Embedding scatter" in html
    finally:
        server.stop()


def test_embedding_coords_and_word_scatter(tmp_path):
    from deeplearning4j_tpu.ui import embedding_coords, render_word_scatter
    rng = np.random.default_rng(0)
    vecs = rng.standard_normal((20, 16))
    pca = embedding_coords(vecs, method="pca")
    assert pca.shape == (20, 2)
    # PCA projection preserves the top-2 covariance directions: reconstruct
    # variance ordering
    assert pca[:, 0].var() >= pca[:, 1].var()
    ts = embedding_coords(vecs[:12], method="tsne", max_iter=50)
    assert ts.shape == (12, 2)

    class _WV:  # minimal WordVectors-protocol stub
        class vocab:
            @staticmethod
            def words():
                return [f"w{i}" for i in range(20)]
        @staticmethod
        def get_word_vector(w):
            return vecs[int(w[1:])]

    out = tmp_path / "words.html"
    html = render_word_scatter(_WV(), path=str(out))
    assert "svg" in html and out.exists()


def test_sqlite_stats_storage(tmp_path):
    """SQLite storage backend (reference ui/storage/sqlite/)."""
    from deeplearning4j_tpu.ui import SqliteStatsStorage, StatsReport
    path = str(tmp_path / "stats.db")
    storage = SqliteStatsStorage(path)
    seen = []
    storage.register_listener(seen.append)
    for it in range(3):
        storage.put_record(StatsReport(
            session_id="s1", worker_id="w0", iteration=it, epoch=0,
            timestamp=it * 1.0, score=1.0 / (it + 1), iter_time_ms=1.0))
    storage.put_record(StatsReport(session_id="s2", worker_id="w1",
                                   iteration=0, epoch=0, timestamp=9.0,
                                   score=0.5, iter_time_ms=1.0))
    assert len(seen) == 4
    assert storage.list_session_ids() == ["s1", "s2"]
    assert storage.list_worker_ids("s1") == ["w0"]
    recs = storage.get_records("s1")
    assert [r.iteration for r in recs] == [0, 1, 2]
    assert storage.get_latest_record("s1").score == pytest.approx(1 / 3)
    # reopen from disk: records survive the process boundary
    storage2 = SqliteStatsStorage(path)
    assert storage2.list_session_ids() == ["s1", "s2"]
    assert storage2.get_records("s2")[0].worker_id == "w1"


def test_param_drilldown_endpoint():
    """Per-parameter drill-down (VERDICT item 6: render what's collected —
    the TrainModule.java model-tab role): series + latest histograms for a
    parameter and its updates."""
    storage = InMemoryStatsStorage()
    server = UIServer(port=0).start()
    server.attach(storage)
    try:
        _train_with(storage, epochs=2, session_id="dd_sess")
        base = f"http://127.0.0.1:{server.port}"
        d = json.load(urllib.request.urlopen(
            f"{base}/train/dd_sess/param/layer_0/W"))
        n = len(d["iterations"])
        assert n == 6
        assert len(d["param_mean_magnitude"]) == n
        assert all(v > 0 for v in d["param_mean_magnitude"])
        assert len(d["param_hist"]) == 20 and sum(d["param_hist"]) > 0
        assert d["param_min"] < d["param_max"]
        # updates exist from the second collected iteration on
        assert any(v is not None for v in d["update_mean_magnitude"])
        assert d["update_hist"] is not None
    finally:
        server.stop()


def test_activation_grid_pages():
    """ConvolutionalIterationListener(url=...) posts land on /activations
    and render into the grids page (ui/module/convolutional role)."""
    server = UIServer(port=0).start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        payload = json.dumps({"iteration": 7, "svg": "<svg>GRID7</svg>"})
        req = urllib.request.Request(
            f"{base}/activations", data=payload.encode(),
            headers={"Content-Type": "application/json"})
        assert json.load(urllib.request.urlopen(req))["ok"]
        html = urllib.request.urlopen(f"{base}/activations").read().decode()
        assert "iteration 7" in html and "GRID7" in html
        # malformed post: 400, server stays alive
        bad = urllib.request.Request(
            f"{base}/activations", data=b"{}",
            headers={"Content-Type": "application/json"})
        try:
            urllib.request.urlopen(bad)
            raise AssertionError("expected 400")
        except urllib.error.HTTPError as e:
            assert e.code == 400
        assert json.load(urllib.request.urlopen(
            f"{base}/train/sessions")) == []
        # entity-encoded script vectors must not slip past the stored-XSS
        # guard (the page embeds accepted svg verbatim)
        # a drawing made of the elements/attrs our listeners actually emit
        # must pass the allowlist
        good = ('<svg width="100" height="50" viewBox="0 0 100 50" '
                'style="background:#fff;margin:8px 0">'
                '<rect x="1" y="2" width="10" height="10" fill="#1f77b4"/>'
                '<polyline points="0,0 5,5" fill="none" stroke="rgb(9,9,9)"'
                ' stroke-width="1.5"/><g transform="translate(3,4)">'
                '<text x="1" y="1" font-size="10" fill="url(#grad)" '
                "stroke=\"url('#g2')\">ok"
                '</text></g></svg>')
        req = urllib.request.Request(
            f"{base}/activations",
            data=json.dumps({"iteration": 2, "svg": good}).encode(),
            headers={"Content-Type": "application/json"})
        assert json.load(urllib.request.urlopen(req))["ok"]
        for evil in (
                '<svg><a xlink:href="java&#115;cript:alert(1)">x</a></svg>',
                '<svg><img &#111;nerror=alert(1)></svg>',
                '<svg>&lt;script&gt;&#60;script&#62;</svg>',
                '<svg><a href="java&#9;script:alert(1)">x</a></svg>',
                '<svg><a href="java&Tab;script:alert(1)">x</a></svg>',
                '<svg><image href=x /onerror=alert(1)></svg>',
                # SMIL attribute-targeting: animates an event handler into
                # existence without any on* attribute in the payload
                '<svg><rect width="5" height="5">'
                '<set attributeName="onmouseover" to="alert(1)"/>'
                '</rect></svg>',
                # external-reference exfil channels
                '<svg><use href="http://evil/x.svg#p"/></svg>',
                '<svg><image href="http://evil/x.png"/></svg>',
                '<svg><rect width="5" height="5"'
                ' fill="url(http://evil/f.svg#x)"/></svg>',
                '<svg><rect width="5" height="5"'
                ' fill="url(http://evil"/></svg>',
                '<svg><style>rect{fill:url(http://evil)}</style></svg>',
                # CSS identifier escape spelling of url( — the browser's
                # CSS parser decodes \\75 to 'u' after the scan would miss
                '<svg><rect width="5" height="5"'
                ' style="fill:\\75rl(http://evil/x)"/></svg>',
                # CDATA is inert in XML but raw <script> once the page
                # embeds the stored string into HTML
                '<svg><text><![CDATA[<script>alert(1)</script>]]>'
                '</text></svg>',
                '<svg><!-- c --><rect onclick="x" width="1"/></svg>'):
            req = urllib.request.Request(
                f"{base}/activations",
                data=json.dumps({"iteration": 1, "svg": evil}).encode(),
                headers={"Content-Type": "application/json"})
            try:
                urllib.request.urlopen(req)
                raise AssertionError(f"expected 400 for {evil!r}")
            except urllib.error.HTTPError as e:
                assert e.code == 400
    finally:
        server.stop()


def test_ui_component_dsl_full_set():
    """Round out the ui-components role: stacked area, timeline,
    horizontal bar, div/accordion containers, styles."""
    from deeplearning4j_tpu.ui import (ChartHorizontalBar, ChartStackedArea,
                                       ChartTimeline, ComponentDiv,
                                       ComponentText, DecoratorAccordion,
                                       StyleChart, StyleDiv, render_page)
    area = (ChartStackedArea(title="memory by pool", x_label="step")
            .set_x([0, 1, 2, 3])
            .add_series("params", [1, 1, 1, 1])
            .add_series("activations", [0, 2, 3, 1]))
    tl = (ChartTimeline(title="phases")
          .add_lane("etl", [(0.0, 1.5, "load")])
          .add_lane("train", [(1.5, 6.0, "fit"), (6.0, 7.0, "eval")]))
    bars = (ChartHorizontalBar(title="per-class F1",
                               style=StyleChart(width=400, height=200))
            .add_bar("setosa", 1.0).add_bar("versicolor", 0.93)
            .add_bar("virginica", -0.1))
    div = ComponentDiv(style=StyleDiv(width=860, margin_px=4)).add(
        ComponentText("grouped"), bars)
    acc = DecoratorAccordion(title="details", default_collapsed=True).add(tl)
    page = render_page([area, div, acc])
    assert page.count("<svg") == 3
    assert "polygon" in page                       # stacked area marks
    assert "<details" in page and "open" not in page.split("<details")[1][:8]
    assert 'width="400"' in page                   # style applied
    assert "setosa" in page and "load" in page
    # guardrails
    import pytest as _pytest
    with _pytest.raises(ValueError, match="set_x"):
        ChartStackedArea().add_series("s", [1, 2])
    with _pytest.raises(ValueError, match="non-negative"):
        (ChartStackedArea().set_x([0, 1])
         .add_series("s", [1, -2]).render())


def test_ui_component_json_roundtrip():
    """Components are wire objects (reference TestComponentSerialization):
    tagged-JSON round-trip preserves the tree and renders identically."""
    from deeplearning4j_tpu.ui import (ChartLine, ComponentDiv,
                                       ComponentTable, ComponentText,
                                       DecoratorAccordion, StyleText,
                                       component_from_json, component_to_json)
    tree = ComponentDiv().add(
        ComponentText("hello", style=StyleText(font_size=20, bold=True)),
        DecoratorAccordion(title="inner").add(
            ChartLine(title="t").add_series("s", [0, 1], [2.0, 3.0]),
            ComponentTable(["a"], [["b"]], title="tbl")))
    s = component_to_json(tree)
    back = component_from_json(s)
    assert type(back) is ComponentDiv
    assert back.render() == tree.render()
    # nested types survive
    assert type(back.children[0].style) is StyleText
    assert back.children[1].children[0].series == [["s", [0.0, 1.0],
                                                    [2.0, 3.0]]]


def test_ui_server_report_page():
    """The server's /train/<sid>/report page is BUILT from the component
    DSL (ui-components consumed by server pages)."""
    storage = InMemoryStatsStorage()
    server = UIServer(port=0).start()
    server.attach(storage)
    try:
        _train_with(storage, epochs=2, session_id="rep_sess")
        base = f"http://127.0.0.1:{server.port}"
        page = urllib.request.urlopen(
            f"{base}/train/rep_sess/report").read().decode()
        assert "Training report" in page and "rep_sess" in page
        assert "<svg" in page                      # DSL charts rendered
        assert "score vs iteration" in page
        assert "<details" in page                  # accordion sections
        assert "summary</caption>" in page or "summary" in page
        empty = urllib.request.urlopen(
            f"{base}/train/ghost/report").read().decode()
        assert "no records" in empty
    finally:
        server.stop()


def test_ui_component_style_values_escaped():
    """Style fields travel over the component_from_json wire between
    hosts, so color/font strings are untrusted: attribute-escaping at
    render time closes the injection vector (ISSUE 1 / ADVICE round 5)."""
    from deeplearning4j_tpu.ui import (ChartLine, ComponentTable,
                                       ComponentText, DecoratorAccordion,
                                       StyleAccordion, StyleChart,
                                       StyleTable, StyleText,
                                       component_from_json,
                                       component_to_json)
    payload = '"><script>alert(1)</script>'
    comps = [
        ComponentText("t", style=StyleText(color=payload, font=payload)),
        ComponentTable(["h"], [["v"]],
                       style=StyleTable(header_color=payload,
                                        background_color=payload)),
        DecoratorAccordion(title="a",
                           style=StyleAccordion(title_color=payload,
                                                background_color=payload)),
        ChartLine(title="c", style=StyleChart(
            axis_stroke=payload,
            series_colors=[payload])).add_series("s", [0, 1], [1.0, 2.0]),
    ]
    for c in comps:
        # escaping must hold on direct render AND after a wire round-trip
        for rendered in (c.render(),
                         component_from_json(component_to_json(c)).render()):
            assert "<script>" not in rendered
            assert "&quot;&gt;&lt;script&gt;" in rendered


def test_ui_chart_horizontal_bar_all_negative_layout():
    """All-negative values: the zero baseline clamps to the right edge and
    every bar/label coordinate stays inside the SVG (regression: the old
    v_max==max(values) put sx(0) far outside the 540px frame)."""
    import re
    from deeplearning4j_tpu.ui import ChartHorizontalBar
    bars = (ChartHorizontalBar(title="losses")
            .add_bar("a", -5.0).add_bar("b", -2.0))
    svg = bars.render()
    w = 540.0  # StyleChart default width
    for m in re.finditer(r'<rect x="([0-9.]+)" [^>]*width="([0-9.]+)"', svg):
        x, bw = float(m.group(1)), float(m.group(2))
        assert 0.0 <= x <= w and x + bw <= w + 0.5, (x, bw)
    for m in re.finditer(r'<text x="([0-9.-]+)"', svg):
        assert -0.5 <= float(m.group(1)) <= w, m.group(0)
    # all-zero degenerate span must not divide by zero
    z = ChartHorizontalBar().add_bar("z", 0.0).render()
    assert "<svg" in z


def test_ui_training_report_pairs_sparse_param_norms():
    """_training_report pairs each parameter's norms with the iterations
    of the records the parameter actually appeared in (regression: the
    old code matched a same-length TAIL of the iteration axis)."""
    from types import SimpleNamespace
    from deeplearning4j_tpu.ui.server import _Handler
    recs = [
        SimpleNamespace(iteration=1, score=0.5, iter_time_ms=1.0,
                        param_stats={"w": {"norm2": 1.0}}),
        SimpleNamespace(iteration=2, score=0.4, iter_time_ms=1.0,
                        param_stats={"w": {"norm2": 1.1}}),
        SimpleNamespace(iteration=3, score=0.3, iter_time_ms=1.0,
                        param_stats={}),   # param absent in the last record
    ]
    page = _Handler._training_report(None, "sid", recs)
    norms_svg = next(s for s in page.split("<svg")
                     if "parameter L2 norms" in s).split("</svg>")[0]
    # x extents of the norms chart: iterations 1..2 (where `w` appeared),
    # NOT the tail 2..3 the old pairing produced
    assert ">1<" in norms_svg and ">2<" in norms_svg
    assert ">3<" not in norms_svg
