"""Sparse embedding gradients (ISSUE 15): the densified row exchange.

Four layers of coverage:

* **carrier units** — ``SparseRows`` pytree round-trip, the int32
  coalesce (sorted unique + slot map, x64-stable), the custom-vjp
  lookup whose backward is one segment-sum, the capacity contract.
* **parity** — the sparse path is BIT-IDENTICAL to the dense path on
  the replicated trainer (params AND updater state after N steps), and
  the lazy row-space updater's one deliberate deviation (untouched-row
  mirrors keep their bytes instead of decaying) is pinned explicitly.
* **sharded** — replicated-sparse == sharded-sparse bitwise at a fixed
  global batch, the row-sharded table + mirrors round-trip
  ``save_sharded``/``restore_sharded`` across dp=4 → dp=2 with exact
  digests, and ONE trace serves every mesh size with zero steady-state
  recompiles (the counter half of the ISSUE 15 acceptance line; the
  IR half — no O(vocab·dim) collective — is pinned in test_audit.py).
* **layer contract** — the id-path validation satellites: float ids
  raise ``InvalidInputError`` instead of truncating, concrete
  out-of-range ids are refused, and the sequence layer's one-hot input
  decodes to a gather with the matmul as an explicit opt-in.
"""
import hashlib

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                NeuralNetConfiguration)
from deeplearning4j_tpu.faulttolerance.checkpoint import CheckpointManager
from deeplearning4j_tpu.nn import sparse as S
from deeplearning4j_tpu.nn.conf.updaters import Adam, Sgd
from deeplearning4j_tpu.nn.layers.feedforward import (EmbeddingLayer,
                                                      EmbeddingSequenceLayer,
                                                      OutputLayer)
from deeplearning4j_tpu.nn.layers.recurrent import RnnOutputLayer
from deeplearning4j_tpu.observability.registry import default_registry
from deeplearning4j_tpu.parallel import (ParallelWrapper, ShardedTrainer,
                                         make_mesh)
from deeplearning4j_tpu.parallel.inference import InvalidInputError

VOCAB, DIM, CLASSES = 48, 8, 4


def embed_net(sparse=True, updater=None, vocab=VOCAB, cap=None, seed=7,
              l2=None):
    lb = (NeuralNetConfiguration.builder().seed(seed)
          .updater(updater or Sgd(learning_rate=0.1)).list())
    lb.layer(EmbeddingLayer(n_in=vocab, n_out=DIM, sparse_grad=sparse,
                            sparse_grad_capacity=cap, l2=l2))
    lb.layer(OutputLayer(n_out=CLASSES, activation="softmax",
                         loss="mcxent"))
    return MultiLayerNetwork(lb.build()).init()


def seq_net(sparse=True, seed=9, timesteps=6, vocab=VOCAB):
    lb = (NeuralNetConfiguration.builder().seed(seed)
          .updater(Sgd(learning_rate=0.1)).list())
    lb.layer(EmbeddingSequenceLayer(n_in=vocab, n_out=DIM,
                                    sparse_grad=sparse))
    lb.layer(RnnOutputLayer(n_out=CLASSES, activation="softmax",
                            loss="mcxent"))
    conf = lb.set_input_type(
        InputType.recurrent(vocab, timesteps)).build()
    return MultiLayerNetwork(conf).init()


def batch(n=16, vocab=VOCAB, seed=0, dupes=True):
    rng = np.random.default_rng(seed)
    hi = vocab // 3 if dupes else vocab   # a third of the vocab: dupes
    idx = rng.integers(0, hi, (n, 1)).astype(np.int32)
    y = np.eye(CLASSES, dtype=np.float32)[idx[:, 0] % CLASSES]
    return idx, y


def leaves(tree):
    return jax.tree_util.tree_leaves(tree)


def assert_trees_equal(a, b):
    la, lb = leaves(a), leaves(b)
    assert len(la) == len(lb)
    for x, z in zip(la, lb):
        np.testing.assert_array_equal(np.array(x), np.array(z))


def digests(params):
    out = {}
    for lname in sorted(params):
        for pname in sorted(params[lname]):
            a = np.ascontiguousarray(np.array(params[lname][pname]))
            out[f"{lname}/{pname}"] = \
                hashlib.sha256(a.tobytes()).hexdigest()
    return out


def compiles():
    c = default_registry().get("training_compile_total")
    return 0.0 if c is None else c.labels("train_step").value


# ------------------------------------------------------------ carrier units
def test_sparse_rows_pytree_and_to_dense():
    sr = S.SparseRows(jnp.array([1, 3, 8], jnp.int32),
                      jnp.arange(6.0, dtype=jnp.float32).reshape(3, 2),
                      n_rows=8)   # index 8 == n_rows: a fill slot
    flat, treedef = jax.tree_util.tree_flatten(sr)
    assert len(flat) == 2                      # indices + values
    back = jax.tree_util.tree_unflatten(treedef, flat)
    assert back.n_rows == 8 and back.capacity == 3 and back.dim == 2
    dense = np.array(sr.to_dense())
    assert dense.shape == (8, 2)
    np.testing.assert_array_equal(dense[1], [0.0, 1.0])
    np.testing.assert_array_equal(dense[3], [2.0, 3.0])
    assert dense.sum() == pytest.approx(0 + 1 + 2 + 3)   # fill dropped
    assert int(sr.touched()) == 2


def test_coalesce_sorts_dedupes_and_maps_every_position():
    ids = jnp.array([[9, 2], [9, 5], [2, 2]], jnp.int32)
    uniq, inv = S.coalesce(ids, capacity=5, n_rows=16)
    np.testing.assert_array_equal(np.array(uniq), [2, 5, 9, 16, 16])
    assert uniq.dtype == jnp.int32 and inv.dtype == jnp.int32
    assert inv.shape == ids.shape
    np.testing.assert_array_equal(np.array(uniq)[np.array(inv)],
                                  np.array(ids))


def test_embedding_lookup_backward_is_the_dense_gather_grad():
    rng = np.random.default_rng(3)
    W = jnp.asarray(rng.standard_normal((10, 4)).astype(np.float32))
    idx = jnp.array([7, 1, 7, 0], jnp.int32)
    ct = jnp.asarray(rng.standard_normal((4, 4)).astype(np.float32))

    def via_custom(w):
        return jnp.sum(S.embedding_lookup(w, idx) * ct)

    def via_gather(w):
        return jnp.sum(w[idx] * ct)

    np.testing.assert_allclose(np.array(jax.grad(via_custom)(W)),
                               np.array(jax.grad(via_gather)(W)),
                               rtol=0, atol=0)


def test_effective_capacity_contract():
    assert S.effective_capacity(16, 1000) == 16        # n_ids bound
    assert S.effective_capacity(5000, 48) == 48        # vocab bound
    assert S.effective_capacity(16, 1000, 64) == 64    # pad up: fine
    assert S.effective_capacity(16, 48, 64) == 48      # clamped to vocab
    with pytest.raises(ValueError, match="sparse_grad_capacity"):
        S.effective_capacity(16, 1000, 8)              # undersized: refuse


# ------------------------------------------------------------------ parity
@pytest.mark.parametrize("updater", [Sgd(learning_rate=0.1),
                                     Adam(learning_rate=0.05)])
def test_sparse_matches_dense_bitwise_on_replicated_trainer(updater):
    """The acceptance parity: same seed, same batches (with duplicate
    ids), N steps — params AND updater state bit-identical to the dense
    path.  (Adam stays exact here because the touched set is constant
    across steps; the varying-touch lazy deviation is pinned below.)"""
    idx, y = batch(seed=11)
    import copy
    a = embed_net(sparse=False, updater=copy.deepcopy(updater))
    b = embed_net(sparse=True, updater=copy.deepcopy(updater))
    for _ in range(4):
        a.fit(idx, y)
        b.fit(idx, y)
    assert a.get_score() == b.get_score()
    assert_trees_equal(a.params, b.params)
    assert_trees_equal(a.opt_state, b.opt_state)


def test_sequence_layer_sparse_matches_dense_bitwise():
    rng = np.random.default_rng(5)
    ids = rng.integers(0, VOCAB, (8, 6)).astype(np.int32)
    y = np.eye(CLASSES, dtype=np.float32)[
        rng.integers(0, CLASSES, (8, 6))].astype(np.float32)
    a, b = seq_net(sparse=False), seq_net(sparse=True)
    for _ in range(3):
        a.fit(ids, y)
        b.fit(ids, y)
    assert_trees_equal(a.params, b.params)


def test_lazy_updater_semantics_pinned():
    """The ONE deliberate deviation from dense updater math: a row's
    Adam mirrors decay every dense step even with zero gradient, but
    the lazy row-space update leaves untouched rows' mirrors
    bit-untouched.  Pinned so the trade is explicit, not accidental."""
    def table_mirrors(net):
        return [l for l in leaves(net.opt_state)
                if getattr(l, "shape", None) == (VOCAB, DIM)]

    touch_0 = np.zeros((4, 1), np.int32)          # row 0 only
    touch_1 = np.ones((4, 1), np.int32)           # row 1 only
    y = np.eye(CLASSES, dtype=np.float32)[np.zeros(4, np.int64)]
    dense = embed_net(sparse=False, updater=Adam(learning_rate=0.05),
                      seed=21)
    lazy = embed_net(sparse=True, updater=Adam(learning_rate=0.05),
                     seed=21)
    for net in (dense, lazy):
        net.fit(touch_0, y)                       # row 0 gets real mu/nu
    after_first = [np.array(m) for m in table_mirrors(lazy)]
    assert any(np.abs(m[0]).sum() > 0 for m in after_first)
    for net in (dense, lazy):
        net.fit(touch_1, y)                       # row 0 now untouched
    for before, after in zip(after_first, table_mirrors(lazy)):
        np.testing.assert_array_equal(before[0], np.array(after)[0])
    # ...while dense Adam decayed row 0's first moment
    dense_mu = [np.array(m) for m in table_mirrors(dense)]
    lazy_mu = [np.array(m) for m in table_mirrors(lazy)]
    assert any(np.abs(d[0] - l[0]).max() > 0
               for d, l in zip(dense_mu, lazy_mu))


def test_rows_touched_stat_rides_gstats():
    idx = np.array([[3], [3], [5], [9]], np.int32)
    y = np.eye(CLASSES, dtype=np.float32)[np.zeros(4, np.int64)]
    net = embed_net(sparse=True)
    net.fit(idx, y)
    assert int(net._last_grad_stats["embedding_rows_touched"]) == 3


def test_traced_invalid_ids_never_corrupt_other_rows():
    """Device-resident batches bypass the host boundary validation (a
    prefetch pipeline's producer validates; materializing here would
    stall the overlap), so the coalesce must defang invalid ids on the
    traced path too: a negative id must NOT wrap into a write of the
    last row, and an id >= vocab must not un-sort the slot map and
    misattribute gradient.  Pinned behavior: invalid positions read the
    clamp row forward and shed their gradient — only validly-touched
    rows change."""
    vocab = 10
    net = embed_net(sparse=True, vocab=vocab)
    W0 = np.array(jax.device_get(net.params["layer_0"]["W"]))
    # jnp array = device-resident: skips the host boundary check, so
    # the invalid ids genuinely reach the compiled step
    ids = jnp.asarray([[-1], [vocab + 2], [3]], jnp.int32)
    y = np.eye(CLASSES, dtype=np.float32)[np.zeros(3, np.int64)]
    net.fit(ids, y)
    W1 = np.array(jax.device_get(net.params["layer_0"]["W"]))
    changed = [r for r in range(vocab)
               if np.abs(W1[r] - W0[r]).max() > 0]
    assert changed == [3]     # not row 9 (wrap), not row 0 (clamp)
    assert int(net._last_grad_stats["embedding_rows_touched"]) == 1


def test_scatter_rows_tree_leaves_integer_table_shaped_state_alone():
    """With capacity == vocab the row-block shape equals the table
    shape; a table-shaped INTEGER state leaf that gather_rows_tree
    passed through must come back from scatter_rows_tree untouched,
    not row-permuted through uniq."""
    W = jnp.arange(12.0, dtype=jnp.float32).reshape(6, 2)
    ids = jnp.array([5, 1, 5, 0, 2, 3], jnp.int32)
    ctx = S.RowContext(W, ids, configured_capacity=6)   # cap == vocab
    assert ctx.capacity == 6
    tree = {"mu": jnp.ones((6, 2), jnp.float32),
            "steps": jnp.arange(12, dtype=jnp.int32).reshape(6, 2)}
    row_view = S.gather_rows_tree(tree, ctx)
    np.testing.assert_array_equal(np.array(row_view["steps"]),
                                  np.array(tree["steps"]))
    back = S.scatter_rows_tree(tree, row_view, ctx)
    np.testing.assert_array_equal(np.array(back["steps"]),
                                  np.array(tree["steps"]))
    np.testing.assert_array_equal(np.array(back["mu"]),
                                  np.array(tree["mu"]))


# ---------------------------------------------------------------- capacity
def test_undersized_capacity_refused_at_trace_time():
    idx, y = batch()
    net = embed_net(sparse=True, cap=4)           # 16 ids > 4 slots
    with pytest.raises(ValueError, match="sparse_grad_capacity"):
        net.fit(idx, y)


def test_padded_capacity_matches_exact_capacity_bitwise():
    idx, y = batch(seed=13)
    auto = embed_net(sparse=True)                 # cap = min(n_ids, vocab)
    padded = embed_net(sparse=True, cap=VOCAB)    # padded block
    for _ in range(3):
        auto.fit(idx, y)
        padded.fit(idx, y)
    assert_trees_equal(auto.params, padded.params)


def test_sparse_grad_off_first_layer_is_a_clear_error():
    lb = (NeuralNetConfiguration.builder().seed(3)
          .updater(Sgd(learning_rate=0.1)).list())
    lb.layer(EmbeddingLayer(n_in=8, n_out=4))
    lb.layer(EmbeddingLayer(n_in=8, n_out=4, sparse_grad=True))
    lb.layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
    net = MultiLayerNetwork(lb.build()).init()
    with pytest.raises(ValueError, match="first layer"):
        net.fit(np.zeros((4, 1), np.int32),
                np.eye(2, dtype=np.float32)[np.zeros(4, np.int64)])


def test_sparse_grad_on_later_layer_rejected_even_with_sparse_layer0():
    """The whole stack is scanned: a valid sparse layer_0 must not let
    a later layer's flag slip through to a silent dense fallback."""
    lb = (NeuralNetConfiguration.builder().seed(3)
          .updater(Sgd(learning_rate=0.1)).list())
    lb.layer(EmbeddingLayer(n_in=16, n_out=4, sparse_grad=True))
    lb.layer(EmbeddingLayer(n_in=4, n_out=8, sparse_grad=True))
    lb.layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
    net = MultiLayerNetwork(lb.build()).init()
    with pytest.raises(ValueError, match="first layer"):
        net.fit(np.zeros((4, 1), np.int32),
                np.eye(2, dtype=np.float32)[np.zeros(4, np.int64)])


def test_out_of_range_ids_refused_at_every_entry_point():
    """The range contract is reachable from the REAL entry points — not
    just eager layer.apply: fit / output / score / the parallel wrapper
    all validate concrete host batches before dispatch (the traced
    gather would clamp silently), for dense and sparse tables alike."""
    bad = np.array([[3], [77]], np.int32)
    y = np.eye(CLASSES, dtype=np.float32)[np.zeros(2, np.int64)]
    for sparse in (False, True):
        net = embed_net(sparse=sparse, vocab=10)
        with pytest.raises(InvalidInputError, match="out of range"):
            net.fit(bad, y)
        with pytest.raises(InvalidInputError, match="out of range"):
            net.output(bad)
        with pytest.raises(InvalidInputError, match="out of range"):
            net.score(x=bad, y=y)
    pw = ParallelWrapper(embed_net(sparse=True, vocab=10),
                         make_mesh(dp=2))
    with pytest.raises(InvalidInputError, match="out of range"):
        pw.fit(bad, y)


def test_sparse_grad_on_computation_graph_is_a_clear_error():
    """No silent dense fallback on the graph runtime either: the
    densified pre-pass is wired into the MLN train step only, so a
    graph vertex with sparse_grad=True must refuse at build time."""
    from deeplearning4j_tpu.nn.computation_graph import ComputationGraph
    from deeplearning4j_tpu.nn.conf.computation_graph import GraphBuilder

    g = GraphBuilder({"updater": Sgd(learning_rate=0.1)})
    g.add_inputs("ids").set_input_types(InputType.feed_forward(1))
    g.add_layer("emb", EmbeddingLayer(n_in=16, n_out=4,
                                      sparse_grad=True), "ids")
    g.add_layer("out", OutputLayer(n_out=2, activation="softmax",
                                   loss="mcxent"), "emb")
    g.set_outputs("out")
    net = ComputationGraph(g.build()).init()
    with pytest.raises(ValueError, match="MultiLayerNetwork"):
        net.fit([np.zeros((4, 1), np.int32)],
                [np.eye(2, dtype=np.float32)[np.zeros(4, np.int64)]])


def test_sparse_grad_one_hot_input_is_a_clear_error():
    """A sparse_grad table fed one-hot batches must refuse, not quietly
    train dense (the O(vocab·dim) exchange the flag removes)."""
    net = embed_net(sparse=True, vocab=8)
    oh = np.eye(8, dtype=np.float32)[np.zeros(4, np.int64)]
    y = np.eye(CLASSES, dtype=np.float32)[np.zeros(4, np.int64)]
    with pytest.raises(ValueError, match="integer id batch"):
        net.fit(oh, y)


def test_sparse_grad_with_l2_is_a_clear_error():
    net = embed_net(sparse=True, l2=1e-4)
    idx, y = batch()
    with pytest.raises(ValueError, match="l1/l2"):
        net.fit(idx, y)


# ------------------------------------------------------------------ sharded
needs_devices = pytest.mark.skipif(len(jax.devices()) < 8,
                                   reason="needs 8 virtual devices")


@needs_devices
@pytest.mark.parametrize("dp", [2, 4])
def test_sharded_sparse_matches_replicated_sparse_bitwise(dp):
    idx, y = batch(seed=17)
    a = embed_net(sparse=True, updater=Adam(learning_rate=0.05), seed=31)
    b = embed_net(sparse=True, updater=Adam(learning_rate=0.05), seed=31)
    mesh = make_mesh(dp=dp)
    pw = ParallelWrapper(a, mesh)
    st = ShardedTrainer(b, mesh, min_shard_size=0)
    for _ in range(3):
        pw.fit(idx, y)
        st.fit(idx, y)
    assert_trees_equal(a.params, b.params)
    assert_trees_equal(a.opt_state, b.opt_state)
    # the table really is row-sharded, not replicated
    spec = str(b.params["layer_0"]["W"].sharding.spec)
    assert "data" in spec


@needs_devices
def test_sharded_table_and_mirrors_reshard_across_dp(tmp_path):
    """save_sharded on dp=4, restore onto dp=2: the row-sharded table
    AND its Adam mirrors round-trip with exact digests, and training
    continues on the new mesh (the issue's checkpoint satellite)."""
    idx, y = batch(seed=19)
    net = embed_net(sparse=True, updater=Adam(learning_rate=0.05),
                    seed=37)
    st = ShardedTrainer(net, make_mesh(dp=4), min_shard_size=0)
    for _ in range(3):
        st.fit(idx, y)
    mgr = CheckpointManager(str(tmp_path / "store"), background=False)
    mgr.save_sharded(net, step=3)
    want = digests(net.params)
    opt_want = [np.array(l) for l in leaves(net.opt_state)]
    net2, _ = mgr.restore_sharded(mesh=make_mesh(dp=2), min_shard_size=0)
    assert digests(net2.params) == want
    for a, b in zip(opt_want, leaves(net2.opt_state)):
        np.testing.assert_array_equal(a, np.array(b))
    st2 = ShardedTrainer(net2, make_mesh(dp=2), min_shard_size=0)
    st2.fit(idx, y)
    assert np.isfinite(net2.get_score())


@needs_devices
def test_one_trace_zero_steady_recompiles_across_mesh_sizes():
    """The counter half of the ISSUE 15 acceptance: the sparse train
    step traces ONCE (sharding lives in the arguments) and steady-state
    fitting — replicated and sharded, any dp — adds zero recompiles."""
    idx, y = batch(seed=23)
    before = compiles()
    nets = [embed_net(sparse=True, seed=41, vocab=64) for _ in range(3)]
    ShardedTrainer(nets[0], make_mesh(dp=2), min_shard_size=0).fit(idx, y)
    ShardedTrainer(nets[1], make_mesh(dp=4), min_shard_size=0).fit(idx, y)
    ParallelWrapper(nets[2], make_mesh(dp=8)).fit(idx, y)
    assert compiles() - before == 1
    steady = compiles()
    for _ in range(4):
        ShardedTrainer(nets[1], make_mesh(dp=4),
                       min_shard_size=0).fit(idx, y)
    assert compiles() - steady == 0


# ----------------------------------------------------------- layer contract
def test_embedding_layer_float_ids_raise_not_truncate():
    lc = EmbeddingLayer(n_in=8, n_out=4, name="emb")
    v = lc.init(jax.random.PRNGKey(0), None)
    with pytest.raises(InvalidInputError, match="integer"):
        lc.apply(v, jnp.asarray([[1.7], [2.2]], jnp.float32))


def test_embedding_layer_out_of_range_concrete_ids_refused():
    lc = EmbeddingLayer(n_in=8, n_out=4, name="emb")
    v = lc.init(jax.random.PRNGKey(0), None)
    with pytest.raises(InvalidInputError, match="out of range"):
        lc.apply(v, jnp.asarray([[3], [8]], jnp.int32))
    with pytest.raises(InvalidInputError, match="out of range"):
        lc.apply(v, jnp.asarray([[-1], [2]], jnp.int32))


def test_embedding_layer_id_column_and_one_hot_still_work():
    lc = EmbeddingLayer(n_in=8, n_out=4, name="emb", has_bias=False)
    v = lc.init(jax.random.PRNGKey(0), None)
    ids = jnp.asarray([[3], [5]], jnp.int32)
    by_id, _ = lc.apply(v, ids)
    one_hot = jax.nn.one_hot(ids[:, 0], 8, dtype=jnp.float32)
    by_oh, _ = lc.apply(v, one_hot)
    np.testing.assert_array_equal(np.array(by_id), np.array(by_oh))
    # n_in == 1 with a [b, 1] float column: the historically ambiguous
    # shape now fails loudly instead of truncating float "ids"
    amb = EmbeddingLayer(n_in=1, n_out=4, name="amb")
    va = amb.init(jax.random.PRNGKey(1), None)
    with pytest.raises(InvalidInputError, match="integer"):
        amb.apply(va, jnp.asarray([[0.9], [0.1]], jnp.float32))


def test_embedding_sequence_layer_validates_ids():
    lc = EmbeddingSequenceLayer(n_in=8, n_out=4, name="seq")
    v = lc.init(jax.random.PRNGKey(0), None)
    with pytest.raises(InvalidInputError, match="integer"):
        lc.apply(v, jnp.asarray([[0.5, 1.5]], jnp.float32))
    with pytest.raises(InvalidInputError, match="out of range"):
        lc.apply(v, jnp.asarray([[1, 9]], jnp.int32))


def test_embedding_sequence_vocab_mismatch_is_a_clear_error():
    """A 3-D input whose trailing dim disagrees with the vocabulary
    (stale tokenizer / wrong vocab size) fails at the API boundary, not
    as a cryptic dot_general shape error deep in the trace."""
    lc = EmbeddingSequenceLayer(n_in=48, n_out=4, name="seq")
    v = lc.init(jax.random.PRNGKey(0), None)
    bad = jnp.zeros((2, 5, 47), jnp.float32)
    with pytest.raises(InvalidInputError, match="vocabulary is 48"):
        lc.apply(v, bad)
    mm = EmbeddingSequenceLayer(n_in=48, n_out=4, name="mm",
                                one_hot_matmul=True)
    with pytest.raises(InvalidInputError, match="vocabulary is 48"):
        mm.apply(v, bad)


def test_embedding_sequence_one_hot_decodes_to_gather():
    """Satellite: an exactly-one-hot [b, t, v] input rides the gather
    (bit-equal to the id path in f32), and the dense matmul survives
    only as the explicit one_hot_matmul opt-in — where it computes the
    same values for exact one-hots."""
    rng = np.random.default_rng(7)
    ids = rng.integers(0, 8, (3, 5)).astype(np.int32)
    oh = np.eye(8, dtype=np.float32)[ids]
    gather_lc = EmbeddingSequenceLayer(n_in=8, n_out=4, name="g")
    matmul_lc = EmbeddingSequenceLayer(n_in=8, n_out=4, name="m",
                                       one_hot_matmul=True)
    v = gather_lc.init(jax.random.PRNGKey(0), None)
    by_ids, _ = gather_lc.apply(v, jnp.asarray(ids))
    by_oh, _ = gather_lc.apply(v, jnp.asarray(oh))
    by_mm, _ = matmul_lc.apply(v, jnp.asarray(oh))
    np.testing.assert_array_equal(np.array(by_ids), np.array(by_oh))
    np.testing.assert_array_equal(np.array(by_oh), np.array(by_mm))
