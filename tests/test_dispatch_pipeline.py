"""Bounded async dispatch window (ISSUE 18): the fit loops may run the
host up to ``DL4J_TPU_DISPATCH_DEPTH`` steps ahead of the device.

The contracts under test:

* the window is pure scheduling — params after a fit are BITWISE
  identical at depth 1 (the serial loop), 2, and 4, including the tBPTT
  chunked path and ragged epoch tails;
* checkpoint boundaries drain the window first, so a mid-window save
  resumes digest-exact even when the resuming run uses a different
  depth;
* a deferred device failure (NaN at step N) surfaces at a drain within
  the window bound, attributed to step N's own iteration via the
  ``nan_at_drain`` flight-recorder event;
* flipping the depth is host-only: zero recompiles across depths;
* the ``training_dispatch_depth`` gauge reads the CONFIGURED depth in
  steady state — the proof the pipeline actually fills.
"""
import os

import numpy as np
import pytest

from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                NeuralNetConfiguration)
from deeplearning4j_tpu.faulttolerance import CheckpointConfig
from deeplearning4j_tpu.nn.conf.updaters import Adam
from deeplearning4j_tpu.nn.dispatch import (DEFAULT_DEPTH, DispatchWindow,
                                            ENV_VAR, configured_depth)
from deeplearning4j_tpu.nn.layers import (DenseLayer, LSTM, OutputLayer,
                                          RnnOutputLayer)
from deeplearning4j_tpu.observability.recorder import (FlightRecorder,
                                                       set_flight_recorder)
from deeplearning4j_tpu.observability.registry import default_registry


def dense_net(seed=42):
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater(Adam(learning_rate=0.02)).list()
            .layer(DenseLayer(n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4)).build())
    return MultiLayerNetwork(conf).init()


def tbptt_net(seed=7, T=12):
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater(Adam(learning_rate=0.01)).list()
            .layer(LSTM(n_out=6, activation="tanh"))
            .layer(RnnOutputLayer(n_out=2, activation="softmax",
                                  loss="mcxent"))
            .backprop_type("tbptt", fwd=4, back=4)
            .set_input_type(InputType.recurrent(3, T)).build())
    return MultiLayerNetwork(conf).init()


def make_batches(n=10, batch=8, seed=0):
    rng = np.random.default_rng(seed)
    return [(rng.standard_normal((batch, 4), dtype=np.float32),
             np.eye(3, dtype=np.float32)[rng.integers(0, 3, batch)])
            for _ in range(n)]


@pytest.fixture
def recorder(tmp_path):
    rec = FlightRecorder(capacity=256, directory=str(tmp_path / "disp"),
                         min_dump_interval_s=0.0)
    prev = set_flight_recorder(rec)
    try:
        yield rec
    finally:
        set_flight_recorder(prev)


def _compile_counts(reg):
    fam = reg.snapshot().get("training_compile_total")
    if not fam:
        return {}
    return {tuple(sorted(s["labels"].items())): s["value"]
            for s in fam["samples"]}


# --------------------------------------------------- window unit semantics

class _Token:
    """Fake loss token: float() is the sync, so the order of float()
    calls IS the materialization order the window promises."""

    def __init__(self, value, log):
        self.value = value
        self.log = log

    def __float__(self):
        self.log.append(self.value)
        return float(self.value)


class _Prof:
    def __init__(self):
        self.calls = []

    def drained(self, k):
        self.calls.append(k)


class TestWindowSemantics:
    def test_configured_depth_env_parsing(self, monkeypatch):
        monkeypatch.delenv(ENV_VAR, raising=False)
        assert configured_depth() == DEFAULT_DEPTH
        monkeypatch.setenv(ENV_VAR, "4")
        assert configured_depth() == 4
        # the window never goes below the serial loop
        monkeypatch.setenv(ENV_VAR, "0")
        assert configured_depth() == 1
        monkeypatch.setenv(ENV_VAR, "-3")
        assert configured_depth() == 1
        monkeypatch.setenv(ENV_VAR, "two")
        assert configured_depth() == DEFAULT_DEPTH
        monkeypatch.setenv(ENV_VAR, "")
        assert configured_depth() == DEFAULT_DEPTH

    def test_push_blocks_oldest_at_depth(self):
        log = []
        win = DispatchWindow(depth=3)
        for i in range(5):
            win.push(_Token(float(i), log), i)
            # at most depth-1 tokens stay un-materialized after a push,
            # so the NEXT dispatch sees at most `depth` in flight
            assert len(win) <= 2
        # FIFO: the oldest token materializes first, every time
        assert log == [0.0, 1.0, 2.0]
        win.drain()
        assert log == [0.0, 1.0, 2.0, 3.0, 4.0] and len(win) == 0

    def test_depth_one_is_the_serial_loop(self):
        log = []
        win = DispatchWindow(depth=1)
        for i in range(3):
            win.push(_Token(float(i), log), i)
            assert len(win) == 0      # every push materializes its own step
        assert log == [0.0, 1.0, 2.0]

    def test_owner_profiler_and_nan_bookkeeping(self):
        log, nans = [], []
        owner = type("Owner", (), {})()
        prof = _Prof()
        win = DispatchWindow(depth=2, owner=owner, profiler=prof,
                             on_nan=lambda it, v: nans.append((it, v)))
        win.push(_Token(0.5, log), 10)
        win.push(_Token(float("nan"), log), 11)
        win.push(_Token(0.25, log), 12)
        win.drain()
        # each drained token updates the owner's drain-boundary view…
        assert owner.last_drained_score == 0.25
        assert owner.last_drained_iteration == 12
        # …ticks the profiler occupancy once per pop…
        assert prof.calls == [1, 1, 1]
        # …and the NaN fired with ITS OWN iteration, not the latest one
        assert nans == [(11, pytest.approx(float("nan"), nan_ok=True))]
        assert nans[0][0] == 11

    def test_abandon_never_blocks(self):
        log = []
        win = DispatchWindow(depth=4)
        win.push(_Token(1.0, log), 0)
        win.push(_Token(2.0, log), 1)
        win.abandon()
        # no float() ran: the exception path must not sync on in-flight
        # work while unwinding
        assert log == [] and len(win) == 0

    def test_drain_timed_returns_iteration_order(self):
        log = []
        win = DispatchWindow(depth=4)
        for i in range(3):
            win.push(_Token(float(i), log), 100 + i)
        out = win.drain_timed()
        assert [it for it, _ in out] == [100, 101, 102]
        assert all(isinstance(t, float) for _, t in out)
        # completion stamps are monotone — the fence's attribution spacing
        assert all(a[1] <= b[1] for a, b in zip(out, out[1:]))


# ------------------------------------------------- fit-loop integration

class TestDepthParity:
    def test_dense_parity_across_depths(self, monkeypatch):
        batches = make_batches(10)
        flats, scores = [], []
        for depth in (1, 2, 4):
            monkeypatch.setenv(ENV_VAR, str(depth))
            net = dense_net()
            net.fit(iter(batches), epochs=2)
            flats.append(net.params_flat())
            scores.append(net.get_score())
            assert net.iteration == 20
        # pure scheduling: bitwise-identical params and score at every
        # depth, not just allclose
        assert np.array_equal(flats[0], flats[1])
        assert np.array_equal(flats[0], flats[2])
        assert scores[0] == scores[1] == scores[2]

    def test_tbptt_and_ragged_tail_parity(self, monkeypatch):
        rng = np.random.default_rng(3)
        T = 12
        seq_batches = [
            (rng.standard_normal((4, T, 3)).astype(np.float32),
             np.eye(2, dtype=np.float32)[
                 rng.integers(0, 2, (4, T))])
            for _ in range(4)]
        # ragged epoch tail: the last batch is smaller, exercising the
        # ShapePolicy bucket path inside the pipelined loop
        tail = (rng.standard_normal((2, T, 3)).astype(np.float32),
                np.eye(2, dtype=np.float32)[rng.integers(0, 2, (2, T))])
        seq_batches.append(tail)
        flats, iters = [], []
        for depth in (1, 2, 4):
            monkeypatch.setenv(ENV_VAR, str(depth))
            net = tbptt_net(T=T)
            net.fit(iter(seq_batches), epochs=2)
            flats.append(net.params_flat())
            iters.append(net.iteration)
        assert np.array_equal(flats[0], flats[1])
        assert np.array_equal(flats[0], flats[2])
        # tBPTT chunking (3 chunks per T=12 batch) counted identically
        assert iters[0] == iters[1] == iters[2]

    def test_zero_steady_recompiles_across_depth_flips(self, monkeypatch):
        batches = make_batches(6)
        net = dense_net()
        net.fit(iter(batches[:2]), epochs=1)      # compile + warm
        reg = default_registry()
        before = _compile_counts(reg)
        for depth in (1, 2, 4, 2, 1):
            monkeypatch.setenv(ENV_VAR, str(depth))
            net.fit(iter(batches), epochs=1)
        # the depth knob is host-only scheduling: no retrace, ever
        assert _compile_counts(reg) == before


class TestCheckpointBoundary:
    def test_mid_window_checkpoint_resume_digest_exact(self, tmp_path,
                                                       monkeypatch):
        batches = make_batches(10)
        monkeypatch.setenv(ENV_VAR, "4")

        netA = dense_net()
        netA.fit(iter(batches), epochs=2)          # uninterrupted

        netB = dense_net()
        cfg = CheckpointConfig(directory=str(tmp_path),
                               save_every_n_iterations=3, keep_last=10,
                               background=False)
        # save cadence 3 vs window depth 4: every save lands mid-window,
        # so each one exercises the due()-drain boundary
        netB.fit(iter(batches), epochs=2, checkpoint=cfg)
        assert np.array_equal(netA.params_flat(), netB.params_flat())

        mgr = cfg.resolve()
        mid = mgr.checkpoints()[1][1]              # "the kill point"
        # resume at a DIFFERENT depth: the checkpoint captured fully
        # materialized state, so the window depth of the resuming run
        # is irrelevant to the result
        monkeypatch.setenv(ENV_VAR, "1")
        netC = dense_net()
        netC.fit(iter(batches), epochs=2, resume_from=mid)
        assert np.array_equal(netA.params_flat(), netC.params_flat())
        assert netC.iteration == netA.iteration


class TestDeferredFailure:
    def test_nan_surfaces_within_window_with_own_iteration(self, recorder,
                                                           monkeypatch):
        monkeypatch.setenv(ENV_VAR, "4")
        batches = make_batches(8)
        bad_x = batches[3][0].copy()
        bad_x[0, 0] = np.nan
        batches[3] = (bad_x, batches[3][1])
        net = dense_net()
        net.fit(iter(batches), epochs=1)
        events = [r for r in recorder.channel("train").items()
                  if r["type"] == "nan_at_drain"]
        assert events, "deferred NaN never surfaced at a drain"
        # batch index 3 is optimizer iteration 4 on a fresh net; the
        # first NaN drain carries THAT iteration even though the host
        # had already dispatched past it
        assert events[0]["iteration"] == 4
        assert events[0]["score"] != events[0]["score"]
        # the poisoned step propagates: every later drain is NaN too,
        # each attributed to its own iteration, in order
        assert [e["iteration"] for e in events] == \
            sorted(e["iteration"] for e in events)
        # and the loop's final materialization saw it as well
        assert net.get_score() != net.get_score()


class TestDepthGauge:
    @pytest.mark.parametrize("depth", [2, 4])
    def test_steady_state_gauge_reads_configured_depth(self, depth,
                                                       recorder,
                                                       monkeypatch):
        monkeypatch.setenv(ENV_VAR, str(depth))
        monkeypatch.setenv("DL4J_TPU_STEPPROF", "1")
        monkeypatch.setenv("DL4J_TPU_STEPPROF_SAMPLE", "6")
        net = dense_net()
        net.fit(iter(make_batches(2)), epochs=1)   # compile + warm
        net.fit(iter(make_batches(12)), epochs=1)
        gauge = default_registry().get("training_dispatch_depth")
        assert gauge is not None
        # the pipeline actually fills: between sampled fences the window
        # holds exactly the configured number of in-flight steps
        assert gauge.value == float(depth)


class TestOverlapGate:
    """The ZeRO-3 gather/compute-overlap flags are TPU-runtime-only:
    on a CPU-pinned rig they must never reach ``os.environ`` — a child
    process inheriting them fatally aborts in XLA's flag parse
    (``Unknown flags in XLA_FLAGS``), even when a libtpu wheel happens
    to be installed on the box."""

    def test_cpu_pinned_rig_never_mutates_xla_flags(self):
        from deeplearning4j_tpu.parallel.sharded import (
            OVERLAP_XLA_FLAGS, enable_gather_compute_overlap)
        before = os.environ.get("XLA_FLAGS", "")
        # tier-1 runs under JAX_PLATFORMS=cpu: the platform is pinned
        # away from TPU, so arming must refuse regardless of libtpu
        assert enable_gather_compute_overlap() is False
        assert os.environ.get("XLA_FLAGS", "") == before
        for flag in OVERLAP_XLA_FLAGS:
            assert flag.split("=")[0] not in \
                os.environ.get("XLA_FLAGS", "")

    def test_platform_pin_parsing(self, monkeypatch):
        from deeplearning4j_tpu.parallel import sharded

        class _Cfg:
            def __init__(self, platforms):
                self.jax_platforms = platforms

        for pinned, expected in [("cpu", False), ("tpu", True),
                                 ("cpu,tpu", True), ("TPU", True),
                                 ("gpu", False), ("", None)]:
            monkeypatch.setattr(sharded.jax, "config", _Cfg(pinned))
            if expected is None:
                # empty config falls through to the environment pin,
                # which tier-1 sets to cpu
                monkeypatch.setenv("JAX_PLATFORMS", "cpu")
                assert sharded._tpu_platform_selected() is False
                monkeypatch.setenv("JAX_PLATFORMS", "tpu")
                assert sharded._tpu_platform_selected() is True
            else:
                assert sharded._tpu_platform_selected() is expected
