"""Gradient compression + training-master tests (reference test model:
``EncodedGradientsAccumulatorTest``-style unit checks plus
``TestSparkMultiLayerParameterAveraging`` / ``GradientSharingTrainingTest``
semantics run on local workers)."""
import numpy as np
import pytest

import jax.numpy as jnp

from deeplearning4j_tpu.data.dataset import DataSet, INDArrayDataSetIterator
from deeplearning4j_tpu.data.mnist import IrisDataSetIterator
from deeplearning4j_tpu.nn.conf.input_type import InputType
from deeplearning4j_tpu.nn.conf.multi_layer import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.updaters import Adam, Sgd
from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel import (ElasticTrainer,
                                         EncodedGradientsAccumulator,
                                         EncodingHandler,
                                         ParameterAveragingTrainingMaster,
                                         SharedGradientsTrainingMaster,
                                         bitmap_decode, bitmap_encode,
                                         threshold_decode, threshold_encode,
                                         tree_average)
from deeplearning4j_tpu.parallel.accumulation import decode


class TestEncoding:
    def test_threshold_roundtrip_and_residual(self):
        rng = np.random.default_rng(0)
        g = rng.standard_normal(512).astype(np.float32) * 0.01
        g[10], g[100], g[300] = 0.5, -0.7, 0.9
        msg, residual = threshold_encode(g, threshold=0.1)
        dec = np.asarray(threshold_decode(msg))
        assert set(np.flatnonzero(dec)) == {10, 100, 300}
        np.testing.assert_allclose(dec[[10, 100, 300]], [0.1, -0.1, 0.1],
                                   rtol=1e-6)
        # decoded + residual reconstructs the original exactly
        np.testing.assert_allclose(dec + np.asarray(residual), g, atol=1e-6)

    def test_threshold_topk_cap_keeps_largest(self):
        g = np.zeros(64, np.float32)
        g[:8] = [1, 2, 3, 4, 5, 6, 7, 8]
        msg, residual = threshold_encode(g, threshold=0.5, max_elements=3)
        assert set(msg["idx"]) == {5, 6, 7}  # three largest magnitudes
        np.testing.assert_allclose(
            np.asarray(threshold_decode(msg)) + np.asarray(residual), g,
            atol=1e-6)

    def test_bitmap_roundtrip(self):
        rng = np.random.default_rng(1)
        g = rng.standard_normal(1001).astype(np.float32)  # non-multiple of 4
        msg, residual = bitmap_encode(g, threshold=0.5)
        dec = np.asarray(bitmap_decode(msg))
        assert dec.shape == g.shape
        np.testing.assert_allclose(dec + np.asarray(residual), g, atol=1e-6)
        assert np.all(np.isin(dec, [-0.5, 0.0, 0.5]))
        # packed density: 2 bits/element
        assert msg["packed"].nbytes == (g.size + 3) // 4

    def test_handler_switches_encoding_and_adapts(self):
        h = EncodingHandler(initial_threshold=0.1, target_density=1e-2)
        dense = np.ones(256, np.float32)  # everything over threshold
        msg = h.encode_update(dense)
        assert msg["kind"] == "bitmap"
        assert h.threshold > 0.1  # boosted
        h2 = EncodingHandler(initial_threshold=0.1, target_density=1e-2)
        for _ in range(3):
            h2.encode_update(np.zeros(256, np.float32))  # no signal at all
        assert h2.threshold < 0.1  # decayed toward min

    def test_handler_residual_accumulates_until_sent(self):
        h = EncodingHandler(initial_threshold=1.0)
        g = np.full(16, 0.4, np.float32)
        m1 = h.encode_update(g)
        assert decode(m1).sum() == 0  # below threshold: nothing sent
        m2 = h.encode_update(g)      # residual 0.4 + 0.4 = 0.8, still below
        m3 = h.encode_update(g)      # 1.2 >= t (t decayed <1): sent as +t
        dec3 = np.asarray(decode(m3))
        assert np.allclose(dec3, m3["threshold"]) and m3["threshold"] > 0.8
        np.testing.assert_allclose(np.asarray(h.residual),
                                   1.2 - m3["threshold"], atol=1e-5)


class TestAccumulator:
    def test_fanout_and_apply(self):
        acc = EncodedGradientsAccumulator(
            3, lambda: EncodingHandler(initial_threshold=0.1))
        g = np.zeros(32, np.float32)
        g[4] = 1.0
        acc.store_update(0, g)
        # peers 1,2 receive it; worker 0 does not
        p = np.zeros(32, np.float32)
        out1 = np.asarray(acc.apply_updates(1, p))
        assert out1[4] == pytest.approx(0.1)
        out0 = np.asarray(acc.apply_updates(0, p))
        assert out0[4] == 0.0
        assert acc.messages_sent == 1 and acc.bytes_sent > 0


def _net(updater=None, seed=7):
    conf = (NeuralNetConfiguration.builder()
            .seed(seed).activation("tanh").weight_init("xavier")
            .updater(updater or Adam(learning_rate=0.02))
            .list()
            .layer(DenseLayer(n_out=8))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    return MultiLayerNetwork(conf).init()


class TestTreeAverage:
    def test_matches_flat_mean(self):
        rng = np.random.default_rng(2)
        trees = [{"a": jnp.asarray(rng.standard_normal((3, 3))),
                  "b": {"c": jnp.asarray(rng.standard_normal(5))}}
                 for _ in range(5)]
        avg = tree_average(trees, depth=2)
        expect = np.mean([np.asarray(t["a"]) for t in trees], axis=0)
        np.testing.assert_allclose(np.asarray(avg["a"]), expect, rtol=1e-6)


class TestMasters:
    def test_parameter_averaging_learns_iris(self):
        net = _net(updater=Adam(learning_rate=0.05))
        it = IrisDataSetIterator(batch_size=10)
        master = ParameterAveragingTrainingMaster(num_workers=3,
                                                  averaging_frequency=2)
        for _ in range(15):
            it.reset()
            master.fit(net, it)
        assert net.evaluate(IrisDataSetIterator(batch_size=50)).accuracy() > 0.9

    def test_shared_gradients_learns_iris(self):
        # fixed threshold ~ update magnitude: async 1-bit-style sharing is
        # noisy by construction; assert substantial learning from the 1/3
        # random baseline, not single-worker parity
        # async threshold-encoded sharing is thread-schedule-dependent by
        # design (lock-free, no barrier); one retry absorbs pathological
        # schedules under parallel test load
        for attempt in range(2):
            net = _net(updater=Sgd(learning_rate=0.05))
            it = IrisDataSetIterator(batch_size=10)
            master = SharedGradientsTrainingMaster(
                num_workers=3, handler_factory=lambda: EncodingHandler(
                    initial_threshold=0.01, decay=1.0, boost=1.0))
            for _ in range(25):
                it.reset()
                master.fit(net, it)
            acc = net.evaluate(IrisDataSetIterator(batch_size=50)).accuracy()
            if acc > 0.75:
                break
        assert acc > 0.75, acc
        assert master.accumulator.messages_sent > 0


class TestElasticTrainer:
    def _batches(self, n=30):
        rng = np.random.default_rng(3)
        x = rng.standard_normal((n * 10, 4)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, n * 10)]
        return lambda: iter(INDArrayDataSetIterator(x, y, batch_size=10))

    def test_resume_skips_done_steps(self, tmp_path):
        net = _net()
        trainer = ElasticTrainer(net, str(tmp_path), save_freq=7)
        done = trainer.fit(self._batches(), max_steps=20)
        assert done == 20
        assert trainer.latest_step() == 20  # tail checkpoint written
        params_after = net.params_flat().copy()
        # simulate crash + restart with a FRESH model
        net2 = _net(seed=99)
        trainer2 = ElasticTrainer(net2, str(tmp_path), save_freq=7)
        resumed_from = trainer2.restore_latest()
        assert resumed_from == 20
        np.testing.assert_allclose(net2.params_flat(), params_after,
                                   atol=1e-6)
        # continue to 30: only 10 more steps consumed
        done2 = trainer2.fit(self._batches(), max_steps=30)
        assert done2 == 30

    def test_keep_last_gc(self, tmp_path):
        net = _net()
        trainer = ElasticTrainer(net, str(tmp_path), save_freq=5, keep_last=2)
        trainer.fit(self._batches(), max_steps=25)
        import os
        # CheckpointManager store layout: committed ckpt-XXXXXXXX dirs
        # under keep_last retention, no ad-hoc zip files
        ckpts = [f for f in os.listdir(tmp_path) if f.startswith("ckpt-")]
        assert len(ckpts) == 2
        assert not [f for f in os.listdir(tmp_path) if f.endswith(".zip")]


def test_master_phase_stats():
    """SparkTrainingStats role: split/broadcast/fit/aggregation timings."""
    net = _net()
    it = IrisDataSetIterator(batch_size=25)
    master = ParameterAveragingTrainingMaster(num_workers=2,
                                              averaging_frequency=1)
    master.fit(net, it)
    d = master.stats.as_dict()
    assert {"split", "broadcast", "fit", "aggregation"} <= set(d)
    assert d["fit"]["total_s"] > 0
    assert "aggregation" in master.stats.stats_text()


class TestRemoteGradientSharing:
    """Broker-transported quantized updates (the Aeron/SilentUpdatesMessage
    role): wire round-trip + cross-worker training over Local and TCP
    brokers."""

    def test_wire_roundtrip(self):
        from deeplearning4j_tpu.parallel.remote import (decode_message_bytes,
                                                        encode_message_bytes)
        msg = {"kind": "threshold", "size": 10, "threshold": 0.5,
               "idx": np.array([1, 7], np.int32),
               "signs": np.array([1, -1], np.int8)}
        wid, seq, back = decode_message_bytes(
            encode_message_bytes(3, msg, seq=17))
        assert wid == 3 and seq == 17 and back["kind"] == "threshold"
        assert back["size"] == 10
        np.testing.assert_array_equal(back["idx"], msg["idx"])
        np.testing.assert_array_equal(back["signs"], msg["signs"])
        bm = {"kind": "bitmap", "size": 8, "threshold": 0.25,
              "packed": np.array([0b01100001, 0b10], np.uint8)}
        wid, seq, back = decode_message_bytes(encode_message_bytes(1, bm))
        assert back["kind"] == "bitmap" and seq == 0
        np.testing.assert_array_equal(back["packed"], bm["packed"])

    def _share_once(self, broker):
        import jax.numpy as jnp
        from deeplearning4j_tpu.parallel.accumulation import EncodingHandler
        from deeplearning4j_tpu.parallel.remote import RemoteGradientSharing
        import time
        w0 = RemoteGradientSharing(broker, 0, handler=EncodingHandler(
            initial_threshold=0.1, decay=1.0, boost=1.0))
        w1 = RemoteGradientSharing(broker, 1, handler=EncodingHandler(
            initial_threshold=0.1, decay=1.0, boost=1.0))
        g = np.zeros(16, np.float32)
        g[3], g[8] = 0.7, -0.9
        w0.publish_update(g)
        time.sleep(0.5)   # allow broker fan-out under load
        params = w1.apply_updates(np.zeros(16, np.float32), timeout=3.0)
        params = np.asarray(params)
        # w1 received ±threshold at the transmitted positions
        assert params[3] > 0 and params[8] < 0
        assert abs(params).sum() > 0
        # w0 does not apply its own echo
        own = np.asarray(w0.apply_updates(np.zeros(16, np.float32),
                                          timeout=0.3))
        assert abs(own).sum() == 0
        assert w0.messages_sent == 1 and w1.messages_applied == 1
        w0.close(); w1.close()

    def test_local_broker_sharing(self):
        from deeplearning4j_tpu.streaming import LocalMessageBroker
        self._share_once(LocalMessageBroker())

    def test_tcp_broker_sharing(self):
        from deeplearning4j_tpu.streaming import TcpMessageBroker
        srv = TcpMessageBroker().serve()
        try:
            self._share_once(srv)
        finally:
            srv.shutdown()


def test_early_stopping_parallel_trainer():
    """EarlyStoppingParallelTrainer role: the standard early-stopping loop
    driving a mesh-sharded ParallelWrapper."""
    import jax
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from deeplearning4j_tpu.earlystopping.config import \
        EarlyStoppingConfiguration
    from deeplearning4j_tpu.earlystopping.savers import InMemoryModelSaver
    from deeplearning4j_tpu.earlystopping.scorecalc import \
        DataSetLossCalculator
    from deeplearning4j_tpu.earlystopping.terminations import \
        MaxEpochsTerminationCondition
    from deeplearning4j_tpu.earlystopping.trainer import \
        EarlyStoppingParallelTrainer
    from deeplearning4j_tpu.parallel import ParallelWrapper, make_mesh

    net = _net(updater=Adam(learning_rate=0.05))
    wrapper = ParallelWrapper(net, make_mesh(8, tp=1))
    train_it = IrisDataSetIterator(batch_size=48)
    conf = EarlyStoppingConfiguration(
        epoch_terminations=[MaxEpochsTerminationCondition(8)],
        score_calculator=DataSetLossCalculator(
            IrisDataSetIterator(batch_size=48)),
        model_saver=InMemoryModelSaver())
    result = EarlyStoppingParallelTrainer(conf, wrapper, train_it).fit()
    assert result.total_epochs <= 8
    assert result.best_model is not None
    assert np.isfinite(result.best_model_score)


def test_zero1_optimizer_state_sharding():
    """Cross-replica weight-update sharding (arXiv:2004.13336 / ZeRO-1):
    optimizer state sharded over the data axis, numerics unchanged."""
    import jax
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from deeplearning4j_tpu.parallel import ParallelWrapper, make_mesh
    rng = np.random.default_rng(0)
    x = rng.standard_normal((32, 4)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 32)]

    def run(shard_opt):
        net = _net(updater=Adam(learning_rate=0.05))
        pw = ParallelWrapper(net, make_mesh(8, tp=1),
                             shard_optimizer_state=shard_opt)
        for _ in range(5):
            pw.fit(x, y)
        return net

    a = run(False)
    b = run(True)
    np.testing.assert_allclose(a.get_score(), b.get_score(), rtol=1e-5)
    # the Adam moments really are sharded over 'data'
    import jax.tree_util as jtu
    sharded = [l for l in jtu.tree_leaves(b.opt_state)
               if hasattr(l, "sharding") and hasattr(l, "ndim") and l.ndim
               and "data" in str(l.sharding)]
    assert sharded, "no optimizer-state leaf carries a data-axis sharding"


class TestDistributedEvalScore:
    """Distributed evaluation/scoring on masters (reference
    SparkDl4jMultiLayer.evaluate map-partitions + IEvaluation.merge,
    calculateScore)."""

    def _trained(self):
        net = _net(updater=Adam(learning_rate=0.05))
        it = IrisDataSetIterator(batch_size=25)
        for _ in range(60):
            it.reset()
            net.fit(it)
        return net

    def test_evaluate_matches_local(self):
        net = self._trained()
        master = ParameterAveragingTrainingMaster(num_workers=3)
        it = IrisDataSetIterator(batch_size=15)
        ev = master.evaluate(net, it)
        it.reset()
        local = net.evaluate(it)
        assert ev.accuracy() == pytest.approx(local.accuracy())
        assert ev.confusion.total() == 150

    def test_score_matches_local(self):
        net = self._trained()
        master = ParameterAveragingTrainingMaster(num_workers=3)
        dist = master.score(net, IrisDataSetIterator(batch_size=15))
        ds = next(iter(IrisDataSetIterator(batch_size=150)))
        local = net.score(x=ds.features, y=ds.labels)
        assert dist == pytest.approx(local, rel=1e-3)

    def test_evaluate_custom_factory(self):
        from deeplearning4j_tpu.evaluation.regression import RegressionEvaluation
        net = self._trained()
        master = ParameterAveragingTrainingMaster(num_workers=2)
        ev = master.evaluate(net, IrisDataSetIterator(batch_size=30),
                             eval_factory=RegressionEvaluation)
        assert ev.average_mean_squared_error() >= 0.0


class TestEarlyStoppingMaster:
    def test_master_trainer_stops_and_returns_best(self):
        from deeplearning4j_tpu.earlystopping import (
            DataSetLossCalculator, EarlyStoppingConfiguration,
            EarlyStoppingMasterTrainer, InMemoryModelSaver,
            MaxEpochsTerminationCondition)
        net = _net(updater=Adam(learning_rate=0.05))
        master = ParameterAveragingTrainingMaster(num_workers=2,
                                                  averaging_frequency=2)
        conf = EarlyStoppingConfiguration(
            score_calculator=DataSetLossCalculator(
                IrisDataSetIterator(batch_size=50)),
            epoch_terminations=[MaxEpochsTerminationCondition(8)],
            model_saver=InMemoryModelSaver())
        result = EarlyStoppingMasterTrainer(
            conf, net, master, IrisDataSetIterator(batch_size=15)).fit()
        assert result.termination_reason == "EpochTerminationCondition"
        assert result.total_epochs <= 8
        assert result.best_model is not None
        # training through the master should have learned something
        assert result.best_model_score < 1.0
