"""Elastic sharded training (ISSUE 13): multi-writer barrier checkpoints
and survivor-mesh recovery.

Fast tests prove the two-phase barrier protocol in-process (two emulated
writers of one store — every shard block is addressable from one
process, so both writers stage complete block sets and restore dedupes
by start offset) and the ``ElasticTrainer`` + ``ShardedTrainer`` wiring:
sharded checkpoint dirs, ``restore_sharded(mesh=survivors)`` rejoin,
survivor-mesh rebuild on membership change, ONE train-step trace across
topology changes.

The ``chaos``-marked tests spawn two REAL OS processes sharing one
store (each training an identical ZeRO-3 replica on its process-local
mesh — this CPU backend executes no cross-process computation) and
hard-kill writers mid-protocol: a non-primary mid-block, the primary
between barrier and commit, the primary on the manifest, and a
partition during the barrier.  Acceptance: no torn checkpoint is ever
restorable, ``latest()`` falls back to the previous complete sharded
dir, and post-recovery param digests EXACTLY match the fault-free run.
"""
import hashlib
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import jax

from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                NeuralNetConfiguration)
from deeplearning4j_tpu.faulttolerance.checkpoint import (
    CheckpointManager, ShardBarrier, ShardBarrierError)
from deeplearning4j_tpu.faulttolerance.cluster import (
    ClusterCoordinator, ClusterMember, ClusterView, FileLeaseStore,
    live_ranks)
from deeplearning4j_tpu.nn.conf.updaters import Adam
from deeplearning4j_tpu.nn.layers.feedforward import (DenseLayer,
                                                      OutputLayer)
from deeplearning4j_tpu.observability.registry import default_registry
from deeplearning4j_tpu.parallel import ShardedTrainer, make_mesh
from deeplearning4j_tpu.parallel.distributed import ElasticTrainer
from deeplearning4j_tpu.parallel.mesh import DATA_AXIS

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices")

HELPER = os.path.join(os.path.dirname(__file__), "helpers",
                      "shard_chaos.py")


def mlp(seed=19, hidden=32, features=8, classes=4):
    b = (NeuralNetConfiguration.builder().seed(seed)
         .updater(Adam(learning_rate=0.02)))
    lb = b.list()
    lb.layer(DenseLayer(n_out=hidden, activation="tanh"))
    lb.layer(OutputLayer(n_out=classes, activation="softmax",
                         loss="mcxent"))
    conf = lb.set_input_type(InputType.feed_forward(features)).build()
    return MultiLayerNetwork(conf).init()


def batch(n=32, features=8, classes=4, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, features)).astype(np.float32)
    y = np.eye(classes, dtype=np.float32)[rng.integers(0, classes, n)]
    return x, y


def batches(n=12, features=8, classes=4, seed=7, bs=8):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        x = rng.standard_normal((bs, features)).astype(np.float32)
        out.append((x, np.eye(classes,
                              dtype=np.float32)[rng.integers(0, classes,
                                                             bs)]))
    return out


def digests(params):
    return {f"{ln}/{pn}": hashlib.sha256(
        np.ascontiguousarray(np.array(params[ln][pn])).tobytes()
    ).hexdigest() for ln in sorted(params) for pn in sorted(params[ln])}


def compiles():
    c = default_registry().get("training_compile_total")
    return 0.0 if c is None else c.labels("train_step").value


def sharded_net(seed=19, dp=4, **kw):
    net = mlp(seed=seed, **kw)
    st = ShardedTrainer(net, make_mesh(dp=dp), min_shard_size=0)
    return net, st


# ------------------------------------------------ barrier protocol (fast)

def _two_writer_save(mgr, net, step, generation=1, timeout_s=10.0,
                     live=None):
    """Emulate both writers of a 2-process world from one process: the
    non-primary stages its block + marker first, then the primary
    commits.  Every shard is addressable here so both stage complete
    block sets — restore dedupes by start offset."""
    mgr.save_sharded(net, process_index=1, process_count=2, step=step,
                     barrier=ShardBarrier(generation=generation,
                                          timeout_s=timeout_s))
    return mgr.save_sharded(
        net, process_index=0, process_count=2, step=step,
        barrier=ShardBarrier(generation=generation, timeout_s=timeout_s,
                             live_fn=live))


def test_two_writer_barrier_commit_restores_cross_topology(tmp_path):
    """Tentpole acceptance: a dp=4 two-writer barrier save commits only
    after both blocks land, and restores onto dp=2 (and dp=8) with exact
    param + updater digests."""
    x, y = batch()
    net, st = sharded_net(dp=4)
    for _ in range(3):
        st.fit(x, y)
    mgr = CheckpointManager(str(tmp_path / "store"), background=False)
    path = _two_writer_save(mgr, net, step=3)
    assert os.path.isdir(path)
    names = sorted(os.listdir(path))
    # both writers' blocks, both generation-fenced markers, one manifest
    assert {"shards-p00.npz", "shards-p01.npz", "block-p00.json",
            "block-p01.json", "topology.json",
            "manifest.json"} <= set(names)
    with open(os.path.join(path, "topology.json")) as f:
        assert json.load(f)["process_count"] == 2
    want = digests(net.params)
    opt_want = [np.array(l) for l in
                jax.tree_util.tree_leaves(net.opt_state)]
    for dp in (2, 8):
        net2, _ = mgr.restore_sharded(path, mesh=make_mesh(dp=dp),
                                      min_shard_size=0)
        assert digests(net2.params) == want
        for a, b in zip(opt_want,
                        jax.tree_util.tree_leaves(net2.opt_state)):
            np.testing.assert_array_equal(a, np.array(b))


def test_barrier_primary_waits_for_late_writer(tmp_path):
    """The barrier is a real rendezvous: the primary blocks until the
    late writer's marker lands, then commits."""
    net, st = sharded_net(seed=23)
    mgr = CheckpointManager(str(tmp_path / "store"), background=False)
    done = {}

    def primary():
        done["path"] = mgr.save_sharded(
            net, process_index=0, process_count=2, step=1,
            barrier=ShardBarrier(generation=7, timeout_s=30))

    th = threading.Thread(target=primary)
    th.start()
    time.sleep(0.3)
    assert th.is_alive()          # still waiting on writer 1's marker
    mgr2 = CheckpointManager(mgr.directory, background=False)
    mgr2.save_sharded(net, process_index=1, process_count=2, step=1,
                      barrier=ShardBarrier(generation=7, timeout_s=30))
    th.join(timeout=30)
    assert not th.is_alive()
    assert os.path.isdir(done["path"])
    assert mgr.latest() == done["path"]


def test_barrier_abort_on_eviction_and_orphan_sweep(tmp_path):
    """Satellite: a writer evicted mid-barrier aborts the round — the
    staging dir is a ``.tmp-`` orphan (never restorable, reclaimed by
    sweep), ``latest()`` still answers the previous complete dir."""
    x, y = batch(seed=3)
    net, st = sharded_net(seed=29)
    st.fit(x, y)
    mgr = CheckpointManager(str(tmp_path / "store"), background=False)
    prev = _two_writer_save(mgr, net, step=1)          # a complete round
    st.fit(x, y)
    with pytest.raises(ShardBarrierError, match="evicted mid-barrier"):
        mgr.save_sharded(net, process_index=0, process_count=2, step=2,
                         barrier=ShardBarrier(generation=2, timeout_s=30,
                                              live_fn=lambda: {0}))
    names = os.listdir(mgr.directory)
    orphans = [n for n in names if n.startswith(".tmp-")]
    assert orphans and not any(n == "ckpt-00000002" for n in names)
    # the orphan is invisible to discovery and never restorable
    assert mgr.latest() == prev
    net2, _ = mgr.restore_sharded(mesh=make_mesh(dp=2), min_shard_size=0)
    assert net2.iteration == 1
    assert mgr.sweep_orphans() == len(orphans)
    assert not any(n.startswith(".tmp-")
                   for n in os.listdir(mgr.directory))
    reg = default_registry()
    c = reg.get("checkpoint_barrier_aborts_total")
    assert c is None or c.labels().value >= 1


def test_barrier_abort_on_timeout(tmp_path):
    net, st = sharded_net(seed=31)
    mgr = CheckpointManager(str(tmp_path / "store"), background=False)
    t0 = time.monotonic()
    with pytest.raises(ShardBarrierError, match="never landed"):
        mgr.save_sharded(net, process_index=0, process_count=2, step=1,
                         barrier=ShardBarrier(generation=1,
                                              timeout_s=0.4))
    assert time.monotonic() - t0 < 10
    assert mgr.latest() is None


def test_stale_generation_writer_cannot_land_block(tmp_path):
    """Satellite: generation fencing end to end.  A stale-generation
    writer stages into a DIFFERENT (orphan) staging dir, and even a
    forged marker with the wrong generation inside the live round's dir
    is rejected — it can never satisfy (or pollute) a newer round."""
    net, st = sharded_net(seed=37)
    mgr = CheckpointManager(str(tmp_path / "store"), background=False)
    final = mgr.path_for(1)
    # the stale writer (missed the gen 3 -> 4 bump) posts its block
    mgr.save_sharded(net, process_index=1, process_count=2, step=1,
                     barrier=ShardBarrier(generation=3, timeout_s=5))
    stale_dir = mgr.barrier_staging(final, 3)
    live_dir = mgr.barrier_staging(final, 4)
    assert os.path.isdir(stale_dir) and stale_dir != live_dir
    # a forged wrong-generation marker inside the live round's dir
    os.makedirs(live_dir, exist_ok=True)
    with open(os.path.join(live_dir, "block-p01.json"), "w") as f:
        json.dump({"process_index": 1, "generation": 3,
                   "complete": True}, f)
    assert mgr._scan_block_markers(live_dir, 4) == set()
    # so the gen-4 primary can only time out — the stale block never
    # lands in the newer round's checkpoint
    with pytest.raises(ShardBarrierError, match="never landed"):
        mgr.save_sharded(net, process_index=0, process_count=2, step=1,
                         barrier=ShardBarrier(generation=4,
                                              timeout_s=0.4))
    assert mgr.latest() is None
    assert mgr.sweep_orphans() >= 2        # both rounds' staging dirs


def test_barrier_chaos_stages_fire_in_order(tmp_path):
    """The torn-store probe windows stay SIGKILL-testable: primary fires
    stages 1 (container staged), 2 (mid-block), 3 (post-barrier,
    pre-manifest), 4 (post-manifest, pre-rename); a non-primary fires
    only stage 2."""
    net, st = sharded_net(seed=41)

    class Probe:
        def __init__(self):
            self.stages = []

        def on_commit_stage(self, step, stage):
            self.stages.append((step, stage))

    mgr = CheckpointManager(str(tmp_path / "store"), background=False)
    mgr.chaos = Probe()
    mgr.save_sharded(net, process_index=1, process_count=2, step=5,
                     barrier=ShardBarrier(generation=1, timeout_s=5))
    assert mgr.chaos.stages == [(5, 2)]
    mgr.chaos = Probe()
    mgr.save_sharded(net, process_index=0, process_count=2, step=5,
                     barrier=ShardBarrier(generation=1, timeout_s=5))
    assert mgr.chaos.stages == [(5, 1), (5, 2), (5, 3), (5, 4)]


def test_live_ranks_reads_leases_without_revoking(tmp_path):
    store = FileLeaseStore(str(tmp_path))
    store.renew(3, ttl_s=10.0)
    store.renew(9, ttl_s=0.01)
    view = ClusterView(generation=1, members=(3, 7, 9))
    time.sleep(0.05)
    assert live_ranks(store, view) == {0}      # 3 -> rank 0; 9 expired
    # reads only: the expired lease file is still there for the
    # coordinator's eviction verdict
    assert store.read(9) is not None


# --------------------------------- elastic trainer over sharded (fast)

def test_elastic_sharded_solo_and_survivor_mesh_restore(tmp_path):
    """Acceptance: ElasticTrainer writes SHARDED checkpoint dirs for a
    ShardedTrainer model; a restart on a smaller survivor mesh skips a
    corrupt newest checkpoint, restores the previous COMPLETE one
    through restore_sharded(mesh=survivors) digest-exact, trains on —
    and the train step keeps ONE trace across the dp=4 -> dp=2 topology
    change (counter-verified)."""
    bs = batches()
    store = str(tmp_path / "run")
    before = compiles()
    net1 = mlp(seed=19, hidden=40)
    t1 = ElasticTrainer(
        ShardedTrainer(net1, make_mesh(dp=4), min_shard_size=0),
        store, save_freq=4, keep_last=3)
    assert t1.fit(lambda: iter(bs)) == len(bs)
    ck = sorted(n for n in os.listdir(store) if n.startswith("ckpt-"))
    assert len(ck) >= 2
    # every committed checkpoint is a sharded dir
    for name in ck:
        assert os.path.isfile(os.path.join(store, name, "topology.json"))
    mgr = CheckpointManager(store, background=False)
    want_prev = digests(mgr.restore_sharded(
        os.path.join(store, ck[-2]))[0].params)

    # corrupt the NEWEST checkpoint's shard file: restore must fall
    # back to the previous complete sharded dir, not abort the rejoin
    newest = os.path.join(store, ck[-1])
    shard = next(f for f in os.listdir(newest) if f.endswith(".npz"))
    with open(os.path.join(newest, shard), "r+b") as f:
        f.seek(30)
        f.write(b"\xde\xad\xbe\xef")

    net2 = mlp(seed=19, hidden=40)
    t2 = ElasticTrainer(
        ShardedTrainer(net2, make_mesh(dp=2), min_shard_size=0),
        store, save_freq=4)
    prev_step = int(ck[-2].split("-")[1])
    step0 = t2.restore_latest()
    assert step0 == prev_step
    # restored onto the dp=2 survivor mesh digest-exact
    assert digests(net2.params) == want_prev
    assert any("data" in str(l.sharding.spec)
               for l in jax.tree_util.tree_leaves(net2.params))
    done = t2.fit(lambda: iter(bs))
    assert done == len(bs)
    assert np.isfinite(net2.get_score())
    # hidden=40 is unique to this test: the dp=4 run, the dp=2 restore
    # and the resumed fit all share ONE Python trace of the train step
    assert compiles() - before == 1


def test_elastic_sharded_membership_loss_rebuilds_survivor_mesh(tmp_path):
    """Tentpole (b): a member lost mid-run aborts its in-flight barrier
    round (never a torn store), is evicted at the next boundary, and the
    survivor rebuilds the mesh over itself via
    restore_sharded(mesh=survivors) — then finishes every batch."""
    bs = batches()
    # prewarm the train-step compile with a throwaway same-topology net:
    # the short fake lease below must expire MID-BARRIER (after the
    # first boundary begins), not during the first step's XLA compile
    warm = mlp(seed=19, hidden=48)
    ShardedTrainer(warm, make_mesh(dp=4), min_shard_size=0).fit_batch(
        bs[0])
    store = FileLeaseStore(str(tmp_path))
    coord = ClusterCoordinator(store, lease_ttl_s=0.4)
    m0 = ClusterMember(store, 0, lease_ttl_s=5.0)
    m0.renew_once()
    net = mlp(seed=19, hidden=48)
    st = ShardedTrainer(net, make_mesh(dp=4), min_shard_size=0)
    t = ElasticTrainer(st, str(tmp_path), save_freq=2, member=m0,
                       coordinator=coord,
                       mesh_factory=lambda w: make_mesh(dp=2 * w),
                       barrier_timeout_s=5.0)
    store.renew(1, ttl_s=0.45)            # will die silently mid-run
    coord.begin_round(0)

    def slow():
        for b in bs:
            time.sleep(0.06)
            yield b

    try:
        n = t.fit(slow)
    finally:
        m0.stop()
    assert n == len(bs) and t.trained_steps == len(bs)
    # the dead member's round aborted instead of tearing the store
    assert t.barrier_aborts >= 1
    assert t.last_view.members == (0,)
    # survivor mesh: dp followed the world size through mesh_factory
    assert st.mesh.shape[DATA_AXIS] == 2
    assert len(t.reshard_events) == 1
    ev = t.reshard_events[0]
    assert ev["dp"] == 2 and ev["world_size"] == 1
    assert ev["via"] == "restore_sharded"
    # every committed checkpoint is complete and restorable
    mgr = CheckpointManager(str(tmp_path), background=False)
    for _, path, manifest in mgr.checkpoints():
        assert manifest.get("sharded")
    assert mgr.latest() is not None
    net2, _ = mgr.restore_sharded(mesh=make_mesh(dp=2), min_shard_size=0)
    assert np.isfinite(
        float(np.sum(np.array(net2.params["layer_0"]["W"]))))


def test_restore_sharded_indivisible_dp_replicates_digest_exact(tmp_path):
    """Satellite: restoring onto a survivor mesh whose dp divides NO
    axis of a leaf falls back to replication per the zero3/min_shard
    rules — digest-exact (re-placement moves bytes, never arithmetic)."""
    x, y = batch(seed=5)
    net, st = sharded_net(seed=43, dp=4, hidden=32, features=8)
    st.fit(x, y)
    mgr = CheckpointManager(str(tmp_path / "store"), background=False)
    mgr.save_sharded(net, step=1)
    want = digests(net.params)
    # dp=3 divides neither 8 nor 32 evenly... except 32 % ... 32=3*10+2:
    # no axis of (8,32)/(32,)/(32,4)/(4,) is divisible by 3 -> P()
    net2, _ = mgr.restore_sharded(mesh=make_mesh(dp=3), min_shard_size=0)
    assert digests(net2.params) == want
    specs = {str(l.sharding.spec)
             for l in jax.tree_util.tree_leaves(net2.params)}
    assert specs == {"PartitionSpec()"}
    # and training continues on the survivor mesh
    st2 = ShardedTrainer(net2, make_mesh(dp=3), min_shard_size=0)
    st2.fit(x, y)
    assert np.isfinite(net2.get_score())


# ------------------------------------------------ chaos (two real writers)

def _run_shard_worker(pid, store, out_json, chaos="", batches_n=12,
                      step_sleep=0.0, lease_ttl=2.0, barrier_timeout=90,
                      timeout=300):
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)          # drop the axon TPU site hook
    env.update({"JAX_PLATFORMS": "cpu",
                "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
                "SC_DIR": str(store), "SC_OUT": str(out_json),
                "SC_PID": str(pid), "SC_BATCHES": str(batches_n),
                "SC_SAVE_FREQ": "4",
                "SC_STEP_SLEEP": str(step_sleep),
                "SC_LEASE_TTL_S": str(lease_ttl),
                "SC_BARRIER_TIMEOUT_S": str(barrier_timeout),
                "SC_CHAOS": chaos})
    log = open(str(out_json) + ".log", "w")
    p = subprocess.Popen([sys.executable, HELPER], env=env, stdout=log,
                         stderr=subprocess.STDOUT)
    p._logfile = log
    p._deadline = time.time() + timeout
    return p


def _finish(procs):
    rcs = []
    try:
        for p in procs:
            try:
                rcs.append(p.wait(timeout=max(p._deadline - time.time(),
                                              10)))
            except subprocess.TimeoutExpired:
                p.kill()
                rcs.append(p.wait(timeout=30))
    finally:
        # a wedged worker must not outlive its test: kill stragglers
        # before surfacing whatever failed
        for p in procs:
            if p.poll() is None:
                p.kill()
            p._logfile.close()
    return rcs


def _read(out_json):
    with open(out_json) as f:
        return json.load(f)


def _log(out_json):
    try:
        with open(str(out_json) + ".log") as f:
            return f.read()
    except OSError:
        return "<no log>"


def _recover_in_process(store, n=12, dp=2):
    """The survivor-mesh recovery phase: a fresh single-process trainer
    restores the store's newest COMPLETE checkpoint onto a dp=``dp``
    mesh and trains the remaining batches."""
    net = None
    from tests.helpers.shard_chaos import build_model, make_batches
    net = build_model()
    st = ShardedTrainer(net, make_mesh(dp=dp), min_shard_size=0)
    t = ElasticTrainer(st, str(store), save_freq=4)
    t.fit(lambda: iter(make_batches(n)))
    from jax.flatten_util import ravel_pytree
    flat, _ = ravel_pytree(net.params)
    flat = np.asarray(flat, np.float64)
    return (hashlib.sha256(flat.tobytes()).hexdigest(),
            t.last_restored_step)


@pytest.fixture(scope="module")
def fault_free(tmp_path_factory):
    """One fault-free two-writer run shared by every chaos test: the
    digest every recovery must reproduce exactly, plus a store whose
    barrier checkpoints prove the multi-writer commit is restorable."""
    root = tmp_path_factory.mktemp("shard_ref")
    store = root / "store"
    outs = [root / "r0.json", root / "r1.json"]
    procs = [_run_shard_worker(i, store, outs[i]) for i in (0, 1)]
    rcs = _finish(procs)
    assert rcs == [0, 0], f"ref run failed:\n{_log(outs[0])}\n" \
                          f"{_log(outs[1])}"
    res = [_read(o) for o in outs]
    assert res[0]["param_digest"] == res[1]["param_digest"]
    assert res[0]["barrier_aborts"] == 0
    return {"store": str(store), "digest": res[0]["param_digest"],
            "results": res}


@pytest.mark.chaos
def test_shard_chaos_fault_free_barrier_store_reshards(fault_free):
    """The fault-free rig itself: every committed checkpoint is a
    complete TWO-writer barrier dir, and the earliest (written while
    both members were live) restores onto dp=2 AND dp=4 with identical
    digests — the cross-topology claim on a real multi-writer store."""
    mgr = CheckpointManager(fault_free["store"], background=False)
    ckpts = mgr.checkpoints()
    assert ckpts
    two_writer = [p for _, p, m in ckpts
                  if os.path.isfile(os.path.join(p, "shards-p01.npz"))]
    assert two_writer, [p for _, p, _ in ckpts]
    path = two_writer[0]
    a, _ = mgr.restore_sharded(path, mesh=make_mesh(dp=2),
                               min_shard_size=0)
    b, _ = mgr.restore_sharded(path, mesh=make_mesh(dp=4),
                               min_shard_size=0)
    da = {k: v for k, v in digests(a.params).items()}
    assert da == digests(b.params)


@pytest.mark.chaos
def test_shard_chaos_non_primary_dies_mid_block(tmp_path, fault_free):
    """A non-primary shard writer hard-dies MID-BLOCK (bytes staged,
    marker never posted) at the final save: the primary's barrier times
    out and aborts, latest() falls back to the previous complete sharded
    dir, and recovery on the survivor mesh matches the fault-free digest
    exactly."""
    store = tmp_path / "store"
    outs = [tmp_path / "r0.json", tmp_path / "r1.json"]
    # lease far beyond the run: the primary's verdict is the bounded
    # barrier TIMEOUT, deterministic regardless of scheduling skew
    procs = [
        _run_shard_worker(0, store, outs[0], lease_ttl=600,
                          barrier_timeout=6),
        _run_shard_worker(1, store, outs[1], chaos="block:12",
                          lease_ttl=600, barrier_timeout=6),
    ]
    rcs = _finish(procs)
    assert rcs[1] == 23, _log(outs[1])          # hard-died mid-block
    assert rcs[0] == 0, _log(outs[0])
    res0 = _read(outs[0])
    assert res0["steps"] == 12
    assert res0["barrier_aborts"] >= 1
    assert res0["param_digest"] == fault_free["digest"]
    # no torn checkpoint: the aborted round is a .tmp- orphan, latest()
    # is the previous complete barrier dir (step 8)
    names = os.listdir(store)
    assert not any(n == "ckpt-00000012" for n in names), names
    assert any(n.startswith(".tmp-") for n in names), names
    mgr = CheckpointManager(str(store), background=False)
    latest = mgr.latest()
    assert latest is not None and latest.endswith("ckpt-00000008")
    # survivor-mesh recovery: restore + train the remaining batches
    digest, resumed = _recover_in_process(store)
    assert resumed == 8
    assert digest == fault_free["digest"]
    # the orphan was swept by the recovery trainer
    assert not any(n.startswith(".tmp-") for n in os.listdir(store))


@pytest.mark.chaos
@pytest.mark.parametrize("mode,step", [("precommit", 8), ("manifest", 8)])
def test_shard_chaos_primary_dies_before_commit(tmp_path, fault_free,
                                                mode, step):
    """The PRIMARY hard-dies after the barrier passed — between barrier
    and commit (stage 3) or on the manifest (stage 4): everything is
    staged, nothing is committed.  Only complete checkpoints remain and
    recovery from the previous complete dir is digest-exact."""
    store = tmp_path / "store"
    outs = [tmp_path / "r0.json", tmp_path / "r1.json"]
    procs = [
        _run_shard_worker(0, store, outs[0], chaos=f"{mode}:{step}"),
        _run_shard_worker(1, store, outs[1]),
    ]
    rcs = _finish(procs)
    assert rcs[0] == 23, _log(outs[0])
    assert rcs[1] == 0, _log(outs[1])
    res1 = _read(outs[1])
    assert res1["steps"] == 12          # the non-primary trains on
    assert res1["param_digest"] == fault_free["digest"]
    names = os.listdir(store)
    assert not any(n == f"ckpt-{step:08d}" for n in names), names
    mgr = CheckpointManager(str(store), background=False)
    latest = mgr.latest()
    assert latest is not None and latest.endswith("ckpt-00000004")
    digest, resumed = _recover_in_process(store)
    assert resumed == 4
    assert digest == fault_free["digest"]


@pytest.mark.chaos
def test_shard_chaos_partition_during_barrier(tmp_path, fault_free):
    """A PARTITIONED member (heartbeats stop, process stalls) expires
    mid-barrier: the primary aborts the round on the eviction verdict,
    the survivors train every remaining batch, the stale member comes
    back fenced out (trains nothing, writes nothing), and the final
    state matches the fault-free run exactly."""
    store = tmp_path / "store"
    outs = [tmp_path / "r0.json", tmp_path / "r1.json"]
    procs = [
        _run_shard_worker(0, store, outs[0], step_sleep=0.3,
                          lease_ttl=4.0),
        _run_shard_worker(1, store, outs[1], step_sleep=0.3,
                          lease_ttl=4.0, chaos="partition:7:25"),
    ]
    rcs = _finish(procs)
    assert rcs == [0, 0], f"{_log(outs[0])}\n{_log(outs[1])}"
    res0, res1 = _read(outs[0]), _read(outs[1])
    assert res0["steps"] == 12
    assert res0["param_digest"] == fault_free["digest"]
    # the partitioned member was fenced out by the generation bump: it
    # consumed the stream but never trained or wrote past the partition
    assert res1["evicted"] is True
    # the primary either aborted a round mid-barrier or evicted the
    # partitioned member at the boundary before the barrier began —
    # both leave ONLY complete checkpoints behind
    mgr = CheckpointManager(str(store), background=False)
    latest = mgr.latest()
    assert latest is not None and latest.endswith("ckpt-00000012")
    digest, resumed = _recover_in_process(store)
    assert resumed == 12
    assert digest == fault_free["digest"]
