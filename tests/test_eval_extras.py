"""Memory reports, ModelGuesser, and evaluation-extras tests (reference test
model: ``eval/EvaluationBinaryTest``, ``eval/EvaluationCalibrationTest``,
``nn/conf/memory`` usage, ``util/ModelGuesserTest``)."""
import os

import numpy as np
import pytest

from deeplearning4j_tpu.evaluation import (ROC, EvaluationBinary,
                                           EvaluationCalibration,
                                           calibration_to_html, rocs_to_html)
from deeplearning4j_tpu.nn.conf.input_type import InputType
from deeplearning4j_tpu.nn.conf.memory import (MemoryUseMode, memory_report)
from deeplearning4j_tpu.nn.conf.multi_layer import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.updaters import Adam
from deeplearning4j_tpu.nn.layers.convolution import (ConvolutionLayer,
                                                      SubsamplingLayer)
from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.utils.model_guesser import (guess_format,
                                                    load_model_guess)
from deeplearning4j_tpu.utils.model_serializer import write_model


class TestEvaluationBinary:
    def test_counts_and_metrics(self):
        labels = np.array([[1, 0], [1, 1], [0, 1], [0, 0]], dtype=float)
        preds = np.array([[0.9, 0.2], [0.8, 0.4], [0.3, 0.9], [0.6, 0.1]])
        ev = EvaluationBinary().eval(labels, preds)
        # column 0: preds>=0.5 -> [1,1,0,1]; labels [1,1,0,0]
        assert ev.tp[0] == 2 and ev.fp[0] == 1 and ev.tn[0] == 1 and ev.fn[0] == 0
        # column 1: preds -> [0,0,1,0]; labels [0,1,1,0]
        assert ev.tp[1] == 1 and ev.fn[1] == 1 and ev.tn[1] == 2
        assert ev.precision(0) == pytest.approx(2 / 3)
        assert ev.recall(0) == pytest.approx(1.0)
        assert 0 < ev.average_f1() <= 1
        assert "label_0" in ev.stats()

    def test_per_label_thresholds_and_merge(self):
        labels = np.array([[1], [0]], dtype=float)
        preds = np.array([[0.4], [0.3]])
        ev = EvaluationBinary(thresholds=[0.35]).eval(labels, preds)
        assert ev.tp[0] == 1 and ev.tn[0] == 1
        ev2 = EvaluationBinary(thresholds=[0.35]).eval(labels, preds)
        ev.merge(ev2)
        assert ev.tp[0] == 2

    def test_2d_per_output_mask(self):
        labels = np.array([[1, 0], [1, 1]], dtype=float)
        preds = np.array([[0.9, 0.1], [0.8, 0.9]])
        mask = np.array([[1, 0], [1, 1]], dtype=float)
        ev = EvaluationBinary().eval(labels, preds, mask=mask)
        assert list(ev.tp) == [2, 1]
        assert ev.tp[0] + ev.fp[0] + ev.tn[0] + ev.fn[0] == 2
        assert ev.tp[1] + ev.fp[1] + ev.tn[1] + ev.fn[1] == 1

    def test_3d_per_output_mask(self):
        labels = np.ones((1, 2, 2))
        preds = np.full((1, 2, 2), 0.9)
        mask = np.zeros((1, 2, 2))
        mask[0, 0, 0] = 1  # only t=0, output 0 counts
        ev = EvaluationBinary().eval(labels, preds, mask=mask)
        assert list(ev.tp) == [1, 0]

    def test_time_series_with_mask(self):
        labels = np.zeros((2, 3, 1))
        labels[0, 0, 0] = 1
        preds = np.full((2, 3, 1), 0.9)
        mask = np.array([[1, 1, 0], [0, 0, 0]], dtype=float)
        ev = EvaluationBinary().eval(labels, preds, mask=mask)
        assert ev.tp[0] + ev.fp[0] + ev.tn[0] + ev.fn[0] == 2  # only unmasked


class TestCalibration:
    def test_perfectly_calibrated(self):
        rng = np.random.default_rng(0)
        p = rng.uniform(0.05, 0.95, size=20000)
        y = (rng.uniform(size=p.size) < p).astype(float)
        # two-class softmax-style layout
        labels = np.stack([1 - y, y], axis=1)
        preds = np.stack([1 - p, p], axis=1)
        cal = EvaluationCalibration(reliability_bins=10).eval(labels, preds)
        assert cal.expected_calibration_error(1) < 0.03
        d = cal.reliability_diagram(1)
        ok = np.isfinite(d.fraction_positives)
        np.testing.assert_allclose(d.mean_predicted_value[ok],
                                   d.fraction_positives[ok], atol=0.1)

    def test_overconfident_has_high_ece(self):
        n = 4000
        rng = np.random.default_rng(1)
        p = np.full(n, 0.95)
        y = (rng.uniform(size=n) < 0.6).astype(float)  # true rate 0.6
        cal = EvaluationCalibration().eval(
            np.stack([1 - y, y], 1), np.stack([1 - p, p], 1))
        assert cal.expected_calibration_error(1) > 0.25

    def test_histograms(self):
        cal = EvaluationCalibration(histogram_bins=10)
        cal.eval(np.array([[0, 1.0]]), np.array([[0.25, 0.75]]))
        h = cal.probability_histogram(1)
        assert h.bin_counts[7] == 1 and h.bin_counts.sum() == 1


class TestHtmlExport:
    def test_roc_and_calibration_html(self, tmp_path):
        rng = np.random.default_rng(2)
        y = rng.integers(0, 2, 500).astype(float)
        p = np.clip(y * 0.6 + rng.uniform(size=500) * 0.4, 0, 1)
        roc = ROC()
        roc.eval(y.reshape(-1, 1), p.reshape(-1, 1))
        html = rocs_to_html(roc)
        assert "<svg" in html and "AUC=" in html
        cal = EvaluationCalibration().eval(
            np.stack([1 - y, y], 1), np.stack([1 - p, p], 1))
        html2 = calibration_to_html(cal)
        assert "Reliability" in html2 and "ECE=" in html2


class TestMemoryReport:
    def _conf(self):
        return (NeuralNetConfiguration.builder()
                .seed(1).activation("relu").weight_init("xavier")
                .updater(Adam(learning_rate=1e-3))
                .list()
                .layer(ConvolutionLayer(n_out=8, kernel_size=(3, 3),
                                        convolution_mode="same"))
                .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
                .layer(DenseLayer(n_out=32))
                .layer(OutputLayer(n_out=10, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.convolutional(8, 8, 1))
                .build())

    def test_param_counts_match_model(self):
        conf = self._conf()
        report = memory_report(conf)
        net = MultiLayerNetwork(conf).init()
        assert report.total_params == net.num_params()

    def test_training_exceeds_inference(self):
        report = memory_report(self._conf())
        tr = report.total_memory_bytes(32, MemoryUseMode.TRAINING)
        inf = report.total_memory_bytes(32, MemoryUseMode.INFERENCE)
        assert tr > inf > 0
        s = report.to_string(32)
        assert "total params" in s and "ConvolutionLayer" in s

    def test_unbuilt_conf_raises(self):
        from deeplearning4j_tpu.nn.conf.multi_layer import MultiLayerConfiguration
        with pytest.raises(ValueError, match="input types"):
            memory_report(MultiLayerConfiguration())

    def test_mixed_precision_and_remat_terms(self):
        """bf16 compute adds a low-precision param copy and halves
        activation bytes; remat halves the saved-activation term."""
        def build(**kw):
            b = (NeuralNetConfiguration.builder()
                 .seed(1).activation("relu").weight_init("xavier")
                 .updater(Adam(learning_rate=1e-3)))
            for k, v in kw.items():
                getattr(b, k)(v)
            return (b.list()
                    .layer(DenseLayer(n_out=64))
                    .layer(OutputLayer(n_out=10, activation="softmax",
                                       loss="mcxent"))
                    .set_input_type(InputType.feed_forward(32)).build())

        base = memory_report(build())
        bf16 = memory_report(build(compute_dtype="bfloat16"))
        remat = memory_report(build(cache_mode="remat"))
        assert bf16.mixed_precision and bf16.activation_bytes == 2
        assert not base.mixed_precision and base.activation_bytes == 4
        assert remat.remat
        b, bb, br = (r.total_memory_bytes(512) for r in (base, bf16, remat))
        assert bb < b            # bf16 activations shrink the bound
        assert br == b           # remat: same boundary-activation bound
        # inference path never casts: bf16 config prices it at full width
        inf_b = base.total_memory_bytes(512, MemoryUseMode.INFERENCE)
        inf_bb = bf16.total_memory_bytes(512, MemoryUseMode.INFERENCE)
        assert inf_b == inf_bb
        # adam: 2 slots per param
        assert base.total_updater_elems == 2 * base.total_params

    def test_graph_report_and_xla_exact(self):
        """memory_report_graph counts every vertex; xla_memory_report
        (XLA buffer assignment — the exact tier) bounds it from below and
        its argument bytes match params+updater within 15%
        (VERDICT item 8: predicted vs measured)."""
        from deeplearning4j_tpu.nn.computation_graph import ComputationGraph
        from deeplearning4j_tpu.nn.conf.computation_graph import (
            ElementWiseVertex, GraphBuilder)
        from deeplearning4j_tpu.nn.conf.memory import (memory_report_graph,
                                                       xla_memory_report)
        g = (GraphBuilder(defaults={"updater": Adam(learning_rate=1e-3),
                                    "activation": "relu",
                                    "weight_init": "xavier"})
             .add_inputs("in")
             .add_layer("d1", DenseLayer(n_out=16), "in")
             .add_layer("d2", DenseLayer(n_out=16), "d1")
             .add_vertex("add", ElementWiseVertex(op="add"), "d1", "d2")
             .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                           loss="mcxent"), "add")
             .set_outputs("out")
             .set_input_types(InputType.feed_forward(8)).build())
        net = ComputationGraph(g).init()
        rep = memory_report_graph(g)
        assert rep.total_params == net.num_params()
        assert rep.activation_elems_per_example >= 16 * 3 + 3
        rng = np.random.default_rng(0)
        x = rng.standard_normal((16, 8)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)]
        exact = xla_memory_report(net, [x], [y])
        if exact is None:
            pytest.skip("memory_analysis unavailable on this backend")
        pred_args = (rep.total_params + rep.total_updater_elems) * 4
        data = x.nbytes + y.nbytes + 8
        measured = exact["argument_bytes"] - data
        assert abs(pred_args - measured) / measured < 0.15
        # (no bound assertion on temp: backend conv scratch such as CPU
        #  im2col is outside the analytic model — see memory.py docstring)
        assert exact["temp_bytes"] > 0


class TestModelGuesser:
    def test_guesses_model_and_stats(self, tmp_path):
        conf = (NeuralNetConfiguration.builder()
                .seed(1).updater(Adam(learning_rate=1e-3)).list()
                .layer(DenseLayer(n_out=4))
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(4)).build())
        net = MultiLayerNetwork(conf).init()
        mpath = str(tmp_path / "m.zip")
        write_model(net, mpath)
        assert guess_format(mpath) == "multi_layer_network"
        loaded = load_model_guess(mpath)
        np.testing.assert_allclose(loaded.params_flat(), net.params_flat())
        # stats log
        from deeplearning4j_tpu.ui import FileStatsStorage
        spath = str(tmp_path / "s.bin")
        FileStatsStorage(spath).close()
        assert guess_format(spath) == "stats_log"

    def test_guesses_word_vectors(self, tmp_path):
        path = str(tmp_path / "vec.txt")
        with open(path, "w") as fh:
            fh.write("2 3\nhello 0.1 0.2 0.3\nworld 0.4 0.5 0.6\n")
        assert guess_format(path) == "word_vectors"
        wv = load_model_guess(path)
        assert wv is not None

    def test_unknown_raises(self, tmp_path):
        p = str(tmp_path / "x.bin")
        with open(p, "wb") as fh:
            fh.write(b"\x00\x01\x02\x03garbage")
        assert guess_format(p) == "unknown"
        with pytest.raises(ValueError):
            load_model_guess(p)


def test_prediction_metadata_error_inspection():
    """Per-example metadata (reference eval/meta/): record which source
    records were misclassified."""
    from deeplearning4j_tpu.evaluation.classification import Evaluation
    ev = Evaluation()
    labels = np.eye(3, dtype=np.float32)[[0, 1, 2, 1]]
    preds = np.eye(3, dtype=np.float32)[[0, 2, 2, 1]]  # example 1 wrong
    ev.eval(labels, preds, record_metadata=["rec_a", "rec_b", "rec_c",
                                            "rec_d"])
    errs = ev.get_prediction_errors()
    assert len(errs) == 1
    assert errs[0].metadata == "rec_b"
    assert errs[0].actual == 1 and errs[0].predicted == 2
    assert {p.metadata for p in ev.get_predictions_by_actual_class(1)} == \
        {"rec_b", "rec_d"}
    assert {p.metadata for p in ev.get_predictions_by_predicted_class(2)} == \
        {"rec_b", "rec_c"}
    with pytest.raises(ValueError, match="metadata entries"):
        ev.eval(labels, preds, record_metadata=["only_one"])


def test_evaluation_json_roundtrip_and_merge():
    """eval/serde role: serialize partial evaluations, merge on a driver."""
    from deeplearning4j_tpu.evaluation.classification import Evaluation
    rng = np.random.default_rng(0)
    labels = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 60)]
    preds = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 60)]
    full = Evaluation()
    full.eval(labels, preds)
    # two workers evaluate halves, ship JSON, driver merges
    parts = []
    for sl in (slice(0, 30), slice(30, 60)):
        ev = Evaluation()
        ev.eval(labels[sl], preds[sl])
        parts.append(Evaluation.from_json(ev.to_json()))
    merged = parts[0]
    merged.merge(parts[1])
    assert merged.accuracy() == pytest.approx(full.accuracy())
    np.testing.assert_array_equal(merged.confusion.matrix,
                                  full.confusion.matrix)
