"""Multi-process training worker (launched by test_distributed.py).

One OS process per 'host': jax.distributed over a loopback coordinator, a
global device mesh spanning both processes' CPU devices, ParallelWrapper
SPMD training, ElasticTrainer checkpoint-restart.  The reference proves its
cluster semantics the same way — local[N] Spark + loopback Aeron
(``BaseSparkTest.java:46``, GradientSharingTrainingTest).

Env: MP_PID, MP_NPROC, MP_PORT, MP_DIR, MP_MAX_STEPS, MP_CRASH_AT
(crash hard — os._exit(17) — before training batch #MP_CRASH_AT).
"""
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))


def main():
    pid = int(os.environ["MP_PID"])
    nproc = int(os.environ["MP_NPROC"])
    port = os.environ["MP_PORT"]
    outdir = os.environ["MP_DIR"]
    max_steps = int(os.environ.get("MP_MAX_STEPS", "10"))
    crash_at = int(os.environ.get("MP_CRASH_AT", "0"))

    from deeplearning4j_tpu.parallel.distributed import (
        ElasticTrainer, global_device_mesh, initialize_distributed)

    assert initialize_distributed(f"127.0.0.1:{port}", nproc, pid)

    import numpy as np

    from deeplearning4j_tpu.nn.conf.input_type import InputType
    from deeplearning4j_tpu.nn.conf.multi_layer import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.updaters import Adam
    from deeplearning4j_tpu.nn.layers.feedforward import (DenseLayer,
                                                          OutputLayer)
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper

    conf = (NeuralNetConfiguration.builder()
            .seed(42).activation("tanh").weight_init("xavier")
            .updater(Adam(learning_rate=0.01))
            .list()
            .layer(DenseLayer(n_out=32))
            .layer(OutputLayer(n_out=4, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(20))
            .build())
    model = MultiLayerNetwork(conf).init()
    # pure DP over all processes' devices — with the process-LOCAL
    # fallback for backends that place multi-process arrays (the
    # place_sharded per-shard path) but refuse to execute a
    # multi-process computation (this CPU rig: "Multiprocess
    # computations aren't implemented").  Identical batches keep the
    # per-process replicas byte-identical either way.
    mesh = global_device_mesh(local_fallback=True)
    pw = ParallelWrapper(model, mesh)

    rng = np.random.default_rng(7)       # identical batches on every process
    all_batches = []
    for _ in range(16):
        x = rng.standard_normal((16, 20)).astype(np.float32)
        y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 16)]
        all_batches.append((x, y))

    def batches():
        for i, b in enumerate(all_batches):
            if crash_at and i == crash_at:
                os._exit(17)             # hard crash mid-run, no cleanup
            yield b

    trainer = ElasticTrainer(pw, os.path.join(outdir, f"ckpt_p{pid}"),
                             save_freq=2)
    steps = trainer.fit(batches, max_steps=max_steps)

    # score computed fresh (not get_score): a restart that resumes at
    # max_steps runs zero new optimizer steps, so the running score
    # would be nan while the restored params are perfectly healthy
    result = {"pid": pid, "steps": steps,
              "resumed_from": trainer.last_restored_step,
              "score": model.score(x=all_batches[-1][0],
                                   y=all_batches[-1][1]),
              "param_sum": float(np.asarray(
                  model.params["layer_0"]["W"]).sum())}
    with open(os.path.join(outdir, f"result_p{pid}.json"), "w") as f:
        json.dump(result, f)
    print(f"[{pid}] done: {result}", flush=True)
    if pid == 0:
        # exit barrier: process 0 hosts the jax.distributed coordination
        # service — exiting while a peer still trains aborts the peer.
        # Wait (bounded) for every peer's durable result first; a
        # crashed peer's result never comes, so the wait is capped.
        import time
        deadline = time.time() + 30
        others = [i for i in range(nproc) if i != pid]
        while time.time() < deadline:
            if all(os.path.exists(os.path.join(outdir,
                                               f"result_p{i}.json"))
                   for i in others):
                break
            time.sleep(0.2)
    # hard-exit: the work is done and the result is durable.  A clean
    # interpreter exit would run the jax.distributed teardown, which
    # SIGABRTs the survivor once it notices a hard-crashed peer — the
    # crash-recovery test needs "survivor completed" to read as rc 0
    os._exit(0)


if __name__ == "__main__":
    main()
