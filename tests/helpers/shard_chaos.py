"""Multi-writer sharded-checkpoint chaos worker (launched by
tests/test_elastic_sharded.py).

One OS process of a two-member sharded training world: a ZeRO-3
``ShardedTrainer`` over this process's LOCAL devices (the CPU backend
executes no multi-process computation — identical batches keep the two
replicas byte-identical, the same posture ``mp_worker.py`` uses), an
``ElasticTrainer`` over a SHARED checkpoint store with lease membership,
and every checkpoint a multi-writer BARRIER save: both processes stage
``shards-pNN.npz`` blocks + generation-fenced markers, the primary
(rank 0) commits only after both blocks land.

Chaos modes (``SC_CHAOS``), all deterministic:

- ``block:<step>``     — THIS writer hard-exits mid-block at the barrier
  save of ``step`` (commit stage 2: shard bytes staged, completion
  marker never posted — the SIGKILL-a-non-primary-mid-block fault);
- ``precommit:<step>`` — THIS writer (run it on the primary) hard-exits
  between the barrier and the commit (stage 3: every block landed,
  nothing committed);
- ``manifest:<step>``  — hard-exit between the manifest write and the
  commit rename (stage 4: the crash_in_commit-on-the-manifest fault);
- ``partition:<batch>:<seconds>`` — at data batch ``<batch>`` THIS
  member stops heartbeating and stalls ``<seconds>`` (a network
  partition: its lease expires mid-barrier, the primary aborts the round
  and the survivors train on);
- unset — run fault-free.

Env: SC_DIR (shared store), SC_OUT (result json), SC_PID, SC_BATCHES,
SC_SAVE_FREQ, SC_STEP_SLEEP, SC_LEASE_TTL_S, SC_BARRIER_TIMEOUT_S,
SC_CHAOS.  The result json carries a sha256 digest over the raveled
final params: the acceptance criterion is digest equality with the
fault-free run — exact, because barrier rounds either commit complete
or abort clean and resume restores params + updater + RNG + cursor.
"""
import hashlib
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=2")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)   # match the test process


def build_model():
    from deeplearning4j_tpu.nn.conf.input_type import InputType
    from deeplearning4j_tpu.nn.conf.multi_layer import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.updaters import Adam
    from deeplearning4j_tpu.nn.layers.feedforward import (DenseLayer,
                                                          OutputLayer)
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    conf = (NeuralNetConfiguration.builder()
            .seed(42).activation("tanh").weight_init("xavier")
            .updater(Adam(learning_rate=0.02))
            .list()
            .layer(DenseLayer(n_out=16))
            .layer(OutputLayer(n_out=4, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(6))
            .build())
    return MultiLayerNetwork(conf).init()


def make_batches(n):
    import numpy as np
    rng = np.random.default_rng(7)
    out = []
    for _ in range(n):
        x = rng.standard_normal((8, 6)).astype(np.float32)
        out.append((x, np.eye(4, dtype=np.float32)[rng.integers(0, 4, 8)]))
    return out


def main():
    import numpy as np

    from deeplearning4j_tpu.faulttolerance.cluster import (
        ClusterCoordinator, ClusterMember, FileLeaseStore)
    from deeplearning4j_tpu.faulttolerance.faults import ChaosSchedule
    from deeplearning4j_tpu.parallel.distributed import ElasticTrainer
    from deeplearning4j_tpu.parallel.mesh import make_mesh
    from deeplearning4j_tpu.parallel.sharded import ShardedTrainer

    store_dir = os.environ["SC_DIR"]
    out = os.environ["SC_OUT"]
    pid = int(os.environ["SC_PID"])
    n_batches = int(os.environ.get("SC_BATCHES", "12"))
    save_freq = int(os.environ.get("SC_SAVE_FREQ", "4"))
    step_sleep = float(os.environ.get("SC_STEP_SLEEP", "0"))
    lease_ttl = float(os.environ.get("SC_LEASE_TTL_S", "2.0"))
    barrier_timeout = float(os.environ.get("SC_BARRIER_TIMEOUT_S", "90"))
    chaos = os.environ.get("SC_CHAOS", "")

    model = build_model()
    mesh = make_mesh(devices=jax.local_devices())   # local dp=2
    st = ShardedTrainer(model, mesh, min_shard_size=0)

    store = FileLeaseStore(store_dir)
    member = ClusterMember(store, pid, lease_ttl_s=lease_ttl).start()
    coordinator = None
    if pid == 0:
        coordinator = ClusterCoordinator(store, lease_ttl_s=lease_ttl)
    trainer = ElasticTrainer(st, store_dir, save_freq=save_freq,
                             keep_last=8, member=member,
                             coordinator=coordinator,
                             barrier_timeout_s=barrier_timeout)

    partition_at = partition_s = None
    if chaos.startswith(("block:", "precommit:", "manifest:")):
        kind, step = chaos.split(":")
        stage = {"block": 2, "precommit": 3, "manifest": 4}[kind]
        trainer.manager.chaos = ChaosSchedule(seed=0).crash_in_commit(
            int(step), stage)
    elif chaos.startswith("partition:"):
        _, partition_at, partition_s = chaos.split(":")
        partition_at, partition_s = int(partition_at), float(partition_s)

    batches = make_batches(n_batches)

    def feed():
        for i, b in enumerate(batches):
            if partition_at is not None and i == partition_at:
                # the partition: heartbeats stop (the lease will expire
                # under the peers' feet) and this member stalls past the
                # primary's eviction verdict
                member._stop.set()
                time.sleep(partition_s)
            if step_sleep:
                time.sleep(step_sleep)
            yield b

    steps = trainer.fit(feed)

    from jax.flatten_util import ravel_pytree
    flat, _ = ravel_pytree(model.params)
    flat = np.asarray(flat, np.float64)
    view = trainer.last_view
    result = {"pid": pid, "steps": steps,
              "resumed_from": trainer.last_restored_step,
              "trained_steps": trainer.trained_steps,
              "barrier_aborts": trainer.barrier_aborts,
              "evicted": bool(view is not None
                              and view.rank_of(pid) is None),
              "param_digest": hashlib.sha256(flat.tobytes()).hexdigest()}
    with open(out, "w") as f:
        json.dump(result, f)
    member.stop()
    print(f"[{pid}] done: {result}", flush=True)


if __name__ == "__main__":
    main()
