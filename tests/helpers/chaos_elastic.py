"""Chaos soak worker for the elastic runtime (launched by
test_cluster.py).

One OS process running an ``ElasticTrainer`` fit over a fixed seeded
workload, with an optional :class:`ChaosSchedule` attack on itself:

- ``CE_CHAOS=kill:<after_s>`` — a chaos-monkey thread SIGKILLs this
  process ``after_s`` seconds after the FIRST committed checkpoint
  appears (so the death provably lands between checkpoints, not before
  the first one);
- ``CE_CHAOS=commit:<step>:<stage>`` — hard ``os._exit`` between the
  checkpoint's staged file writes (the ``CheckpointManager.chaos``
  hook): the commit rename never runs, recovery must skip the ``.tmp-``
  orphan;
- unset — run to completion.

Env: CE_DIR (checkpoint store), CE_OUT (result json path), CE_BATCHES,
CE_SAVE_FREQ, CE_STEP_SLEEP (per-batch sleep so a timed kill lands
mid-run), CE_CHAOS.

The result json carries a sha256 digest over the final raveled params:
the chaos acceptance criterion is digest equality with the fault-free
run — exact, not approximate, because resume restores params + updater +
RNG + cursor.
"""
import hashlib
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))


def build_model():
    from deeplearning4j_tpu.nn.conf.input_type import InputType
    from deeplearning4j_tpu.nn.conf.multi_layer import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.updaters import Adam
    from deeplearning4j_tpu.nn.layers.feedforward import (DenseLayer,
                                                          OutputLayer)
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    conf = (NeuralNetConfiguration.builder()
            .seed(42).activation("tanh").weight_init("xavier")
            .updater(Adam(learning_rate=0.02))
            .list()
            .layer(DenseLayer(n_out=16))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(6))
            .build())
    return MultiLayerNetwork(conf).init()


def main():
    store = os.environ["CE_DIR"]
    out = os.environ["CE_OUT"]
    n_batches = int(os.environ.get("CE_BATCHES", "24"))
    save_freq = int(os.environ.get("CE_SAVE_FREQ", "4"))
    step_sleep = float(os.environ.get("CE_STEP_SLEEP", "0"))
    chaos = os.environ.get("CE_CHAOS", "")

    import numpy as np

    from deeplearning4j_tpu.faulttolerance.faults import ChaosSchedule
    from deeplearning4j_tpu.parallel.distributed import ElasticTrainer

    model = build_model()
    rng = np.random.default_rng(7)
    all_batches = []
    for _ in range(n_batches):
        x = rng.standard_normal((8, 6)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)]
        all_batches.append((x, y))

    trainer = ElasticTrainer(model, store, save_freq=save_freq, keep_last=3)

    if chaos.startswith("kill:"):
        after_s = float(chaos.split(":")[1])
        sched = ChaosSchedule(seed=0).kill_process(0, after_s)
        pid = os.getpid()

        def arm_after_first_checkpoint():
            # the monkey clock starts only once a committed checkpoint
            # exists: the SIGKILL lands BETWEEN checkpoints by design
            while not any(name.startswith("ckpt-")
                          for name in os.listdir(store)
                          if os.path.isdir(os.path.join(store, name))):
                time.sleep(0.02)
            sched.start(lambda: {0: pid})

        threading.Thread(target=arm_after_first_checkpoint,
                         daemon=True).start()
    elif chaos.startswith("commit:"):
        _, step, stage = chaos.split(":")
        trainer.manager.chaos = ChaosSchedule(seed=0).crash_in_commit(
            int(step), int(stage))

    def batches():
        for b in all_batches:
            if step_sleep:
                time.sleep(step_sleep)
            yield b

    steps = trainer.fit(batches)

    from jax.flatten_util import ravel_pytree
    flat, _ = ravel_pytree(model.params)
    flat = np.asarray(flat, np.float64)
    result = {"steps": steps,
              "resumed_from": trainer.last_restored_step,
              "param_sum": float(flat.sum()),
              "param_digest": hashlib.sha256(flat.tobytes()).hexdigest()}
    with open(out, "w") as f:
        json.dump(result, f)
    print(f"done: {result}", flush=True)


if __name__ == "__main__":
    main()
