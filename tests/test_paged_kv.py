"""Paged KV cache + shared-prefix prefill (ISSUE 19; the dense
``SlotRing`` and its ``DL4J_TPU_KV_PAGED=0`` escape hatch were removed
in ISSUE 20, so the dense-vs-paged parity pins live on as paged-only
regressions).

The acceptance spine:

* bit parity: greedy token streams through the paged block-pool cache
  are IDENTICAL to the naive full-forward oracle, the whole mixed
  greedy+sampled workload is invariant to block geometry, and streams
  match the per-version greedy oracles across a mid-flight hot-swap
  migration (re-prefilled through the paged path);
* the two-slot COW aliasing regression: a request appending into a
  partially-filled shared prefix block copies first — a later request
  adopting the same shared block still reads the ORIGINAL tokens' K/V;
* allocator honesty: lowest-free-block allocation, vacate-time release,
  trash-block writability invariant, pool-exhaustion starvation that
  fails the starved request loudly and leaves the engine serving;
* int8 KV (``PrecisionPolicy.kv_dtype``): greedy parity within
  tolerance at roughly half the cache bytes;
* zero steady recompiles across a mixed paged workload, and the
  retired ``DL4J_TPU_KV_PAGED`` env var being ignored (paged is the
  only cache organization).
"""
import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.data.shapes import suffix_prefill_buckets
from deeplearning4j_tpu.generation import (GenerationConfig,
                                           GenerationEngine,
                                           StaticSlotSource)
from deeplearning4j_tpu.generation.cache import PagedKV
from deeplearning4j_tpu.models import TransformerLM

VOCAB = 17


@pytest.fixture(scope="module")
def lm():
    return TransformerLM(vocab_size=VOCAB, seq_len=32, embed=16,
                         n_layers=2, n_heads=2).init()


def naive_greedy(net, history, n):
    hist = [int(t) for t in history]
    out = []
    for _ in range(n):
        probs = np.asarray(net.output(np.asarray([hist], np.int32)))
        tok = int(probs[0, len(hist) - 1].argmax())
        out.append(tok)
        hist.append(tok)
    return out


def wait_until(pred, timeout_s=30.0, interval_s=0.005):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval_s)
    return pred()


def run_requests(engine, requests):
    """Submit all, then collect — exercises concurrent slot residency.
    Per-request determinism is the (seed, token_index) RNG contract, so
    batch composition cannot perturb the comparison."""
    handles = [engine.submit(p, **kw) for p, kw in requests]
    return [h.future.result(timeout=120).tokens for h in handles]


REQUESTS = [
    ([3, 1, 4, 1, 5], dict(max_new_tokens=8, seed=11)),
    ([9, 2, 6], dict(max_new_tokens=8, temperature=0.7, top_k=5, seed=42)),
    ([5, 3, 5, 8, 9, 7, 9, 3], dict(max_new_tokens=6, temperature=1.1,
                                    top_p=0.8, seed=7)),
    ([2, 7, 1], dict(max_new_tokens=8, temperature=0.4, seed=13)),
]


# ----------------------------------------------------------- bit parity
class TestPagedParity:
    def test_paged_streams_pin_oracle_and_block_geometry(self, lm):
        """THE parity gate, paged-only since the dense ring's removal:
        greedy streams are bit-identical to the naive full-forward
        oracle, and the whole mixed greedy+sampled workload is invariant
        to block geometry (block size / slot count change WHERE K/V
        lives, never the tokens) across enough concurrent requests to
        exercise block allocation, trash-lane padding and the
        written-prefix mask tail."""
        a = GenerationEngine.for_model(
            lm, GenerationConfig(max_slots=4, max_seq=32, block_size=4))
        try:
            want = run_requests(a, REQUESTS)
            assert a.steady_recompiles == 0
        finally:
            a.shutdown()
        for (prompt, kw), toks in zip(REQUESTS, want):
            if not kw.get("temperature"):          # greedy requests
                assert toks == naive_greedy(lm, prompt, len(toks))
        b = GenerationEngine.for_model(
            lm, GenerationConfig(max_slots=2, max_seq=32, block_size=8))
        try:
            got = run_requests(b, REQUESTS)
            assert b.steady_recompiles == 0
        finally:
            b.shutdown()
        assert got == want

    def test_prefix_sharing_streams_stay_bit_identical(self, lm):
        """Sharing is a pure prefill-work optimization: with a common
        prompt header registered by the first request, later requests
        adopt its blocks and prefill only their suffix — and every
        stream still matches the sharing-disabled engine bit for bit."""
        header = [3, 1, 4, 1, 5, 9, 2, 6]       # two full 4-token blocks
        reqs = [(header + tail, dict(max_new_tokens=6, temperature=0.6,
                                     seed=100 + i))
                for i, tail in enumerate(([7], [8, 2], [9, 9, 1], [4]))]
        cold = GenerationEngine.for_model(
            lm, GenerationConfig(max_slots=2, max_seq=32,
                                 block_size=4, prefix_sharing=False))
        try:
            want = [cold.generate(p, **kw).tokens for p, kw in reqs]
        finally:
            cold.shutdown()
        shared = GenerationEngine.for_model(
            lm, GenerationConfig(max_slots=2, max_seq=32,
                                 block_size=4, prefix_sharing=True))
        try:
            got = [shared.generate(p, **kw).tokens for p, kw in reqs]
            kv = shared.status()["kv"]
            assert kv["prefix_hits"] == 3        # every request after #1
            assert kv["prefix_tokens_saved"] > 0
            assert shared.steady_recompiles == 0
        finally:
            shared.shutdown()
        assert got == want

    def test_cow_two_slot_aliasing_regression(self, lm):
        """The COW pin: request B appends into a PARTIALLY-filled
        shared block (6-token prompt = one full + half a 4-token block),
        request C adopts the same shared prefix afterwards.  Without
        copy-on-write B's first decode write lands in the registered
        block and C gathers B's K/V — caught here as a stream diverging
        from the sharing-disabled reference."""
        prompt_a = [3, 1, 4, 1, 5, 9]            # 1 full block + 2-token tail
        reqs = [
            (prompt_a, dict(max_new_tokens=6, seed=1)),
            (prompt_a + [2, 6, 5, 3], dict(max_new_tokens=6, seed=2)),
            (prompt_a, dict(max_new_tokens=6, temperature=0.5, seed=3)),
            (prompt_a + [8], dict(max_new_tokens=6, seed=4)),
        ]
        cold = GenerationEngine.for_model(
            lm, GenerationConfig(max_slots=2, max_seq=32,
                                 block_size=4, prefix_sharing=False))
        try:
            want = [cold.generate(p, **kw).tokens for p, kw in reqs]
        finally:
            cold.shutdown()
        shared = GenerationEngine.for_model(
            lm, GenerationConfig(max_slots=2, max_seq=32,
                                 block_size=4, prefix_sharing=True))
        try:
            got = [shared.generate(p, **kw).tokens for p, kw in reqs]
            kv = shared.status()["kv"]
            assert kv["cow_copies"] >= 1         # the partial tail was COWed
            assert kv["prefix_hits"] >= 2
            events = [t["event"] for t in shared.ring.trail()]
            assert "cow" in events and "shared_hit" in events
        finally:
            shared.shutdown()
        assert got == want

    def test_migration_reprefills_through_paged_path(self, lm, monkeypatch):
        """Hot-swap during active paged decode: every sequence migrates
        at a step boundary by re-prefilling its own history through the
        paged path — v1-era tokens match the old net's greedy oracle,
        v2-era tokens the new net's continued from the v1 history, and
        the swap costs zero steady recompiles.  The prefix registry is
        invalidated (old-version K/V must never be adopted)."""
        import jax

        net_b = lm.clone()
        net_b.params = jax.tree_util.tree_map(lambda a: a * 1.07,
                                              net_b.params)
        src = StaticSlotSource(lm)
        eng = GenerationEngine(
            src, GenerationConfig(max_slots=2, max_seq=32, block_size=4))
        # deterministic mid-flight swap: park the engine INSIDE its 3rd
        # v1 decode step, swap while it's parked, then let the step
        # finish (still old weights — the engine resolved the model at
        # tick start); the NEXT tick observes the new version and
        # migrates.  A wall-clock wait_until here raced: 15 warm decode
        # ticks can outrun the test thread under full-suite load.
        parked, resume = threading.Event(), threading.Event()
        calls = {"n": 0}
        orig = lm._get_jitted

        def gated(kind):
            fn = orig(kind)
            if kind != "paged_decode":
                return fn

            def stepped(*a, **kw):
                calls["n"] += 1
                if calls["n"] == 3:
                    parked.set()
                    resume.wait(60)
                return fn(*a, **kw)
            return stepped

        try:
            eng.warmup()
            # seed the registry so invalidation has something to drop
            eng.generate([3, 1, 4, 1, 5], max_new_tokens=2, timeout=60)
            assert eng.ring.stats()["blocks_registered"] > 0
            monkeypatch.setattr(lm, "_get_jitted", gated)
            req = eng.submit([9, 2, 6], max_new_tokens=16, seed=5)
            assert parked.wait(60)
            src.swap(net_b)                       # mid-flight, engine parked
            resume.set()
            res = req.future.result(timeout=120)
            toks, vers = res.tokens, res.versions
            assert len(toks) == 16
            assert vers == sorted(vers)
            k = vers.index(2) if 2 in vers else len(toks)
            assert 0 < k < len(toks)              # swap landed mid-flight
            assert toks[:k] == naive_greedy(lm, [9, 2, 6], k)
            assert toks[k:] == naive_greedy(net_b, [9, 2, 6] + toks[:k],
                                            len(toks) - k)
            assert eng.steady_recompiles == 0
            assert any(t["event"] == "migrate" and t["request"] == req.id
                       for t in eng.ring.trail())
        finally:
            eng.shutdown()


# ------------------------------------------------------------- allocator
class TestPagedAllocator:
    def test_lowest_free_alloc_release_and_trail(self, lm):
        kv = PagedKV(lm.conf, max_slots=2, max_seq=32, block_size=8,
                     prefix_sharing=False)
        assert kv.blocks_per_slot == 4
        total_free = kv.blocks_free
        assert total_free == kv.n_blocks - 1      # trash block reserved
        s = kv.acquire("req-a")
        assert all(b == PagedKV.TRASH for b in kv.tables[s])
        assert kv.ensure_blocks(s, "req-a", 1)
        assert kv.tables[s, 0] == 1               # lowest free first
        assert kv.ensure_blocks(s, "req-a", 9)    # spills into 2nd block
        assert kv.tables[s, 1] == 2
        kv.check_writable(s)                      # private block: fine
        assert kv.blocks_free == total_free - 2
        events = [t["event"] for t in kv.trail()]
        assert "block_alloc" in events
        kv.release(s)
        assert kv.blocks_free == total_free       # vacate releases all
        assert any(t["event"] == "block_release" for t in kv.trail())

    def test_trash_write_target_is_refused(self, lm):
        kv = PagedKV(lm.conf, max_slots=1, max_seq=32, block_size=8,
                     prefix_sharing=False)
        s = kv.acquire("req-a")
        with pytest.raises(RuntimeError, match="trash"):
            kv.check_writable(s)                  # no block allocated yet

    def test_pool_exhaustion_is_reported_not_silent(self, lm):
        # 2 slots x 4 blocks each but only 4 usable blocks in the pool
        kv = PagedKV(lm.conf, max_slots=2, max_seq=32, block_size=8,
                     n_blocks=5, prefix_sharing=False)
        s0, s1 = kv.acquire("a"), kv.acquire("b")
        assert kv.ensure_blocks(s0, "a", 16)      # takes 2 of 4
        assert kv.ensure_blocks(s1, "b", 16)      # takes the other 2
        assert not kv.ensure_blocks(s1, "b", 17)  # pool dry: False, loudly
        kv.release(s0)
        assert kv.ensure_blocks(s1, "b", 17)      # recovery after release

    def test_suffix_ladder_floor_follows_block_size(self):
        assert suffix_prefill_buckets(32, 4)[0] == 4
        assert suffix_prefill_buckets(32, 16)[0] == 8
        assert suffix_prefill_buckets(32, 4)[-1] == 32


# ------------------------------------------------------- engine behavior
class TestPagedEngine:
    def test_mixed_workload_zero_steady_recompiles(self, lm):
        eng = GenerationEngine.for_model(
            lm, GenerationConfig(max_slots=4, max_seq=32, block_size=4))
        try:
            run_requests(eng, REQUESTS)
            run_requests(eng, list(reversed(REQUESTS)))
            assert eng.steady_recompiles == 0
            st = eng.status()
            assert st["kv_paged"] is True
            assert st["kv"]["block_size"] == 4
            assert st["cache_bytes"] == eng.ring.cache_bytes
        finally:
            eng.shutdown()

    def test_retired_env_escape_hatch_is_ignored(self, lm, monkeypatch):
        """The ``DL4J_TPU_KV_PAGED=0`` hatch went with the dense ring:
        the env var does nothing and every engine builds the paged
        pool."""
        monkeypatch.setenv("DL4J_TPU_KV_PAGED", "0")
        eng = GenerationEngine.for_model(
            lm, GenerationConfig(max_slots=2, max_seq=32), start=False)
        try:
            eng.warmup()
            assert isinstance(eng.ring, PagedKV)
            assert eng.status()["kv_paged"] is True
            assert eng.status()["kv"] is not None
        finally:
            eng.shutdown()

    def test_pool_exhaustion_fails_starved_request_and_recovers(self, lm):
        """An under-provisioned pool starves a mid-decode slot: that
        request fails LOUDLY (blocks_exhausted vacate in the trail),
        already-satisfied requests finish, and the freed blocks serve
        the next request normally."""
        eng = GenerationEngine.for_model(
            lm, GenerationConfig(max_slots=2, max_seq=32, block_size=8,
                                 n_blocks=5, prefix_sharing=False))
        try:
            # 4 usable 8-token blocks: each request wants 4+14=18 tokens
            # (3 blocks) — together they exceed the pool mid-decode
            ra = eng.submit([3, 1, 4, 1], max_new_tokens=14, seed=1)
            rb = eng.submit([9, 2, 6, 5], max_new_tokens=14, seed=2)
            results, failures = [], []
            for r in (ra, rb):
                try:
                    results.append(r.future.result(timeout=120))
                except RuntimeError as e:
                    failures.append(str(e))
            assert len(failures) >= 1
            assert any("block" in f for f in failures)
            assert any(t["event"] == "vacate"
                       and t.get("reason") == "blocks_exhausted"
                       for t in eng.ring.trail())
            # engine survives and the freed pool serves a fresh request
            res = eng.generate([2, 7], max_new_tokens=4, timeout=60)
            assert res.finish == "length"
            assert eng.ring.active_slots == 0
        finally:
            eng.shutdown()

    def test_decode_exception_dump_attaches_block_events(
            self, lm, tmp_path, monkeypatch):
        """Migration honesty (ISSUE 19 satellite): the occupancy trail a
        decode-exception flight dump carries includes the paged block
        lifecycle — block_alloc at admission rides in the same trail the
        dump snapshots."""
        from deeplearning4j_tpu.observability import (FlightRecorder,
                                                      load_dump)
        from deeplearning4j_tpu.observability.recorder import \
            set_flight_recorder
        rec = FlightRecorder(directory=str(tmp_path),
                             min_dump_interval_s=0.0)
        prev = set_flight_recorder(rec)
        orig = lm._get_jitted

        def patched(kind):
            fn = orig(kind)
            if kind == "paged_decode":
                def boom(*a, **k):
                    raise RuntimeError("injected paged decode fault")
                return boom
            return fn

        monkeypatch.setattr(lm, "_get_jitted", patched)
        eng = GenerationEngine.for_model(
            lm, GenerationConfig(max_slots=2, max_seq=32, block_size=4))
        try:
            req = eng.submit([1, 2, 3], max_new_tokens=6, seed=9)
            with pytest.raises(RuntimeError, match="injected paged"):
                req.future.result(timeout=60)
            assert rec.dumps
            payload = load_dump(rec.dumps[0])
            errs = [r for r in payload["channels"]["decode"]
                    if r["type"] == "decode_error"]
            assert errs
            occ = errs[0]["occupancy"]
            assert occ.get("paged") is True
            events = [t["event"] for t in occ["trail"]]
            assert "block_alloc" in events
            assert any(t["event"] == "install" and t["request"] == req.id
                       for t in occ["trail"])
        finally:
            set_flight_recorder(prev)
            eng.shutdown()


# ---------------------------------------------------------------- int8 KV
def _int8_lm(kv_dtype=None, seed=5):
    """The TransformerLM stack hand-built so the precision policy (and
    its kv_dtype) can be attached — same topology as the module lm."""
    from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                    NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.conf.updaters import Adam
    from deeplearning4j_tpu.nn.layers.attention import (
        PositionalEncodingLayer, TransformerBlock)
    from deeplearning4j_tpu.nn.layers.feedforward import \
        EmbeddingSequenceLayer
    from deeplearning4j_tpu.nn.layers.recurrent import RnnOutputLayer
    from deeplearning4j_tpu.nn.precision import PrecisionPolicy

    b = (NeuralNetConfiguration.builder().seed(seed)
         .updater(Adam(learning_rate=3e-4)).weight_init("xavier"))
    if kv_dtype is not None:
        b = b.precision(PrecisionPolicy(kv_dtype=kv_dtype))
    lb = (b.list()
          .layer(EmbeddingSequenceLayer(n_out=16))
          .layer(PositionalEncodingLayer())
          .layer(TransformerBlock(n_heads=2, causal=True))
          .layer(TransformerBlock(n_heads=2, causal=True))
          .layer(RnnOutputLayer(n_out=VOCAB, activation="softmax",
                                loss="mcxent")))
    conf = lb.set_input_type(InputType.recurrent(VOCAB, 32)).build()
    return MultiLayerNetwork(conf).init()


class TestInt8KV:
    def test_int8_kv_halves_cache_bytes_with_greedy_parity(self):
        """``PrecisionPolicy.kv_dtype='int8'``: K/V pools store one byte
        per element (+ f32 per-token/per-head scales) — under half the
        f32 pool bytes at head_dim 8 — and greedy streams match the f32
        cache within tolerance (identical params; only cache storage
        differs)."""
        f32 = _int8_lm(kv_dtype=None)
        i8 = _int8_lm(kv_dtype="int8")
        # identical init: the policy changes storage, not parameters
        import jax
        for a, b in zip(jax.tree_util.tree_leaves(f32.params),
                        jax.tree_util.tree_leaves(i8.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        prompts = [[3, 1, 4, 1, 5], [9, 2, 6], [5, 3, 5, 8]]
        cfg = dict(max_slots=2, max_seq=32, block_size=4)
        e32 = GenerationEngine.for_model(f32, GenerationConfig(**cfg))
        try:
            want = [e32.generate(p, max_new_tokens=8, timeout=60).tokens
                    for p in prompts]
            f32_bytes = e32.ring.cache_bytes
        finally:
            e32.shutdown()
        e8 = GenerationEngine.for_model(i8, GenerationConfig(**cfg))
        try:
            got = [e8.generate(p, max_new_tokens=8, timeout=60).tokens
                   for p in prompts]
            i8_bytes = e8.ring.cache_bytes
            assert e8.ring.kv_dtype == "int8"
            assert e8.status()["kv"]["kv_dtype"] == "int8"
        finally:
            e8.shutdown()
        assert i8_bytes <= 0.5 * f32_bytes
        # greedy-parity-within-tolerance: argmax is robust to the <=1%
        # relative quantization error at these magnitudes; a rare tied
        # logit may flip one tail token, never the stream wholesale
        same = sum(int(g == w) for g, w in zip(got, want))
        assert same >= len(prompts) - 1, (got, want)
