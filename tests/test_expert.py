"""Expert parallelism (MoE with all-to-all dispatch) on the virtual
8-device CPU mesh — completes the dp/tp/pp/sp/ep taxonomy."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
try:
    from jax import shard_map
except ImportError:  # jax < 0.5 keeps it in experimental
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from deeplearning4j_tpu.parallel.expert import (init_moe_params,
                                                make_moe_train_step, moe_ffn)

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices")

EMBED, HIDDEN, EXPERTS = 8, 16, 4


def _mesh(dp=2, ep=4):
    return Mesh(np.array(jax.devices()[:dp * ep]).reshape(dp, ep),
                ("data", "expert"))


def _data(tokens=64, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((tokens, EMBED)).astype(np.float32)
    # learnable target: a fixed linear map + nonlinearity
    w = rng.standard_normal((EMBED, EMBED)).astype(np.float32) * 0.5
    y = np.tanh(x @ w)
    return jnp.asarray(x), jnp.asarray(y)


def test_sharded_moe_matches_single_device():
    """With capacity ≥ tokens (no drops) the expert-parallel output equals
    the single-device computation."""
    mesh = _mesh()
    params = init_moe_params(jax.random.PRNGKey(0), EXPERTS, EMBED, HIDDEN)
    x, _ = _data(tokens=64)
    # single device: full expert stack, full token set
    ref, _aux = moe_ffn(params, x, capacity=64)

    local_cap = 64 // 8  # per-device tokens (8 tokens) → no drops

    def fwd(p, xx):
        out, aux = moe_ffn(p, xx, capacity=local_cap, expert_axis="expert")
        return out

    pspec = {"router": P(None, None), "w1": P("expert"), "w2": P("expert")}
    fn = jax.jit(shard_map(
        fwd, mesh=mesh,
        in_specs=(pspec, P(("data", "expert"), None)),
        out_specs=P(("data", "expert"), None)))
    got = fn(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


def test_moe_train_step_learns():
    mesh = _mesh()
    params = init_moe_params(jax.random.PRNGKey(1), EXPERTS, EMBED, HIDDEN)
    x, y = _data(tokens=64, seed=3)
    step = make_moe_train_step(capacity=8, lr=0.05)
    # w1/w2 expert-sharded; router replicated; tokens sharded over both axes
    pspec = {"router": P(None, None), "w1": P("expert"), "w2": P("expert")}
    fn = jax.jit(shard_map(
        step, mesh=mesh,
        in_specs=(pspec, P(("data", "expert"), None),
                  P(("data", "expert"), None)),
        out_specs=(pspec, P())))
    losses = []
    # 200 steps: top-1 routing tie-breaks differ across jax versions and
    # the older shard_map converges slower here (0.28 @ 80 steps, 0.16 @
    # 200) — the budget keeps the 0.4x bar meaningful on both
    for _ in range(200):
        params, loss = fn(params, x, y)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < 0.4 * losses[0], losses[:3] + losses[-3:]


def test_capacity_drops_tokens_gracefully():
    """Over-capacity tokens are dropped (zero contribution), not an error."""
    params = init_moe_params(jax.random.PRNGKey(2), EXPERTS, EMBED, HIDDEN)
    x, _ = _data(tokens=32)
    out_small, _ = moe_ffn(params, x, capacity=1)
    out_big, _ = moe_ffn(params, x, capacity=32)
    assert np.isfinite(np.asarray(out_small)).all()
    # dropped tokens produce zero rows; with ample capacity they don't
    zero_rows_small = int((np.abs(np.asarray(out_small)).sum(1) < 1e-9).sum())
    zero_rows_big = int((np.abs(np.asarray(out_big)).sum(1) < 1e-9).sum())
    assert zero_rows_small > zero_rows_big


class TestMoeLayer:
    """MixtureOfExpertsLayer in the config DSL (single-chip path; aux loss
    threaded through state)."""

    def _net(self, cdtype=None):
        from deeplearning4j_tpu.nn.conf.input_type import InputType
        from deeplearning4j_tpu.nn.conf.multi_layer import \
            NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.updaters import Adam
        from deeplearning4j_tpu.nn.layers import (MixtureOfExpertsLayer,
                                                  OutputLayer)
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        b = (NeuralNetConfiguration.builder().seed(11)
             .updater(Adam(learning_rate=0.02)))
        if cdtype:
            b = b.compute_dtype(cdtype)
        conf = (b.list()
                .layer(MixtureOfExpertsLayer(n_out=8, n_experts=4,
                                             hidden=16, activation="relu"))
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(6)).build())
        return MultiLayerNetwork(conf).init()

    def _data(self):
        rng = np.random.default_rng(4)
        y_cls = rng.integers(0, 3, 96)
        x = rng.standard_normal((96, 6)).astype(np.float32) * 0.3
        x[:, :3] += np.eye(3, dtype=np.float32)[y_cls] * 2
        return x, np.eye(3, dtype=np.float32)[y_cls]

    def test_learns_and_tracks_aux(self):
        net = self._net()
        x, y = self._data()
        s0 = net.score(x=x, y=y)
        for _ in range(60):
            net.fit(x, y)
        assert net.score() < 0.4 * s0
        aux = float(np.asarray(net.state["layer_0"]["aux_loss"]))
        assert np.isfinite(aux) and aux >= 0
        assert net.evaluate(x, y).accuracy() > 0.9

    def test_works_under_remat_and_bf16(self):
        import jax
        net = self._net(cdtype="bfloat16")
        net.conf.defaults["cache_mode"] = "remat"
        x, y = self._data()
        for _ in range(5):
            net.fit(x, y)
        assert np.isfinite(net.score())
        for leaf in jax.tree_util.tree_leaves(net.params):
            assert leaf.dtype == jnp.float32


def test_moe_layer_rnn_input():
    """MoE layer consumes [b, t, f] natively (no flatten preprocessor)."""
    from deeplearning4j_tpu.nn.conf.input_type import InputType
    from deeplearning4j_tpu.nn.conf.multi_layer import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.updaters import Adam
    from deeplearning4j_tpu.nn.layers import MixtureOfExpertsLayer
    from deeplearning4j_tpu.nn.layers.recurrent import RnnOutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    conf = (NeuralNetConfiguration.builder().seed(2)
            .updater(Adam(learning_rate=0.02)).list()
            .layer(MixtureOfExpertsLayer(n_out=8, n_experts=2, hidden=16,
                                         activation="relu"))
            .layer(RnnOutputLayer(n_out=3, activation="softmax",
                                  loss="mcxent"))
            .set_input_type(InputType.recurrent(5, 7)).build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    x = rng.standard_normal((4, 7, 5)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, (4, 7))]
    net.fit(x, y)
    out = np.asarray(net.output(x))
    assert out.shape == (4, 7, 3)
    np.testing.assert_allclose(out.sum(-1), 1.0, rtol=1e-4)


def test_moe_layer_gradient_check():
    """Central-difference check (the GradientCheckUtil oracle) on the MoE
    layer: away from routing-decision boundaries the dispatch is constant,
    so analytic grads must match numeric ones."""
    from deeplearning4j_tpu.nn.conf.input_type import InputType
    from deeplearning4j_tpu.nn.conf.multi_layer import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.updaters import Sgd
    from deeplearning4j_tpu.nn.layers import MixtureOfExpertsLayer
    from deeplearning4j_tpu.nn.layers.feedforward import OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.utils.gradient_check import check_gradients
    conf = (NeuralNetConfiguration.builder().seed(3)
            .updater(Sgd(learning_rate=0.1)).list()
            .layer(MixtureOfExpertsLayer(n_out=5, n_experts=2, hidden=6,
                                         capacity_factor=2.0,
                                         activation="tanh"))
            .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4)).build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    x = rng.standard_normal((6, 4))
    y = np.eye(2)[rng.integers(0, 2, 6)]
    assert check_gradients(net, x, y, subset=40)


def test_switch_transformer_block_moe():
    """TransformerBlock(moe_experts>0): Switch-style sparse FFN — trains,
    aux loss tracked in state, KV-cached decode still matches full fwd."""
    from deeplearning4j_tpu.models import TransformerLM
    from deeplearning4j_tpu.nn.conf.updaters import Adam
    net = TransformerLM(vocab_size=11, seq_len=8, embed=16, n_layers=2,
                        n_heads=2, moe_experts=4,
                        updater=Adam(learning_rate=3e-3)).init()
    rng = np.random.default_rng(2)
    starts = rng.integers(0, 11, 16)
    x = (starts[:, None] + np.arange(8)[None, :]) % 11
    y = np.eye(11, dtype=np.float32)[(x + 1) % 11]
    s0 = net.score(x=x, y=y)
    for _ in range(60):
        net.fit(x, y)
    assert net.score() < 0.4 * s0
    aux = float(np.asarray(net.state["layer_2"]["aux_loss"]))
    assert np.isfinite(aux) and aux >= 0
    full = np.asarray(net.output(x))
    net.rnn_clear_previous_state()
    a = np.asarray(net.rnn_time_step(x[:, :3]))
    b = np.asarray(net.rnn_time_step(x[:, 3:]))
    inc = np.concatenate([a, b], axis=1)
    # MoE capacity depends on token count, so routing/drops differ between
    # full-batch and chunked streams; require close, not identical
    assert np.mean(np.abs(inc - full)) < 0.05
