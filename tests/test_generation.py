"""Autoregressive generation subsystem (ISSUE 11): ring KV cache,
two-program prefill/decode, iteration-level continuous batching.

The acceptance spine:

* steady-state generation uses EXACTLY two compiled programs (one
  bucketed prefill per prompt bucket + one fixed-shape decode), counter-
  verified across a mixed workload of ragged prompts, mid-flight joins
  and completions — ``serving_steady_recompiles_total`` stays 0;
* continuous batching is proven at the engine level: a late request
  joins a RUNNING decode batch and its token stream is bit-identical to
  the same request run alone (per-slot RNG streams);
* hot-swap safety: a weight swap during active decode migrates every
  sequence onto the new weights at a step boundary — no sequence mixes
  weight versions, reported versions never move backwards (the PR 8
  swap contract extended to the decode path, under concurrent
  streaming HTTP clients);
* admission/health: slot exhaustion sheds with
  ``serving_shed_total{reason="no_slots"}``, generation readiness rides
  both servers' ``/health``, and a decode-step exception commits a
  flight-recorder dump carrying the slot occupancy trail.
"""
import threading
import time
from concurrent.futures import TimeoutError as FuturesTimeout

import numpy as np
import pytest

from deeplearning4j_tpu.data.shapes import prefill_buckets
from deeplearning4j_tpu.generation import (GenerationConfig,
                                           GenerationEngine, sample_tokens)
from deeplearning4j_tpu.models import TransformerLM
from deeplearning4j_tpu.observability import MetricsRegistry
from deeplearning4j_tpu.observability.registry import default_registry
from deeplearning4j_tpu.parallel.inference import InvalidInputError
from deeplearning4j_tpu.serving.engine import ShedError

VOCAB = 17


@pytest.fixture(scope="module")
def lm():
    """One tiny causal LM for the whole module: every engine built over
    it shares the process-global prefill/decode programs, so the compile
    cost is paid once."""
    return TransformerLM(vocab_size=VOCAB, seq_len=32, embed=16,
                         n_layers=2, n_heads=2).init()


def naive_greedy(net, history, n):
    """The pre-subsystem serving path: one FULL re-forward per token."""
    hist = [int(t) for t in history]
    out = []
    for _ in range(n):
        probs = np.asarray(net.output(np.asarray([hist], np.int32)))
        tok = int(probs[0, len(hist) - 1].argmax())
        out.append(tok)
        hist.append(tok)
    return out


def compiles(fn):
    c = default_registry().get("training_compile_total")
    return 0.0 if c is None else c.labels(fn).value


def wait_until(pred, timeout_s=30.0, interval_s=0.005):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval_s)
    return pred()


# ------------------------------------------------------------ bucket ladder
class TestPrefillBuckets:
    def test_pow2_ladder_tops_out_at_capacity(self):
        assert prefill_buckets(256) == [8, 16, 32, 64, 128, 256]
        # a non-pow2 capacity is still the top bucket (migration must be
        # able to re-prefill the longest sequence the cache holds)
        assert prefill_buckets(48) == [8, 16, 32, 48]
        assert prefill_buckets(8) == [8]
        assert prefill_buckets(4) == [4]

    def test_explicit_ladder_sorted_deduped_capped(self):
        assert prefill_buckets(64, [32, 8, 8, 999]) == [8, 32, 64]
        with pytest.raises(ValueError):
            prefill_buckets(16, [999])
        with pytest.raises(ValueError):
            prefill_buckets(0)


# ---------------------------------------------------------- traced sampling
class TestSampleTokens:
    def _logp(self, rows=2, seed=0):
        rng = np.random.default_rng(seed)
        return np.asarray(rng.standard_normal((rows, VOCAB)) * 3.0,
                          np.float32)

    def _keys(self, rows, seed=7):
        rng = np.random.default_rng(seed)
        return rng.integers(0, 2 ** 32, (rows, 2), dtype=np.uint32)

    def test_zero_temperature_is_argmax(self):
        lp = self._logp(4)
        toks = np.asarray(sample_tokens(
            lp, self._keys(4), np.zeros(4, np.float32),
            np.zeros(4, np.int32), np.ones(4, np.float32)))
        np.testing.assert_array_equal(toks, lp.argmax(-1))

    def test_top_k_one_and_tiny_top_p_collapse_to_argmax(self):
        lp = self._logp(3, seed=1)
        t = np.full(3, 0.9, np.float32)
        k1 = np.asarray(sample_tokens(lp, self._keys(3), t,
                                      np.ones(3, np.int32),
                                      np.ones(3, np.float32)))
        np.testing.assert_array_equal(k1, lp.argmax(-1))
        p0 = np.asarray(sample_tokens(lp, self._keys(3), t,
                                      np.zeros(3, np.int32),
                                      np.full(3, 1e-6, np.float32)))
        np.testing.assert_array_equal(p0, lp.argmax(-1))

    def test_top_k_restricts_support(self):
        lp = self._logp(1, seed=2)
        allowed = set(np.argsort(-lp[0])[:3].tolist())
        for ks in range(40):
            tok = int(np.asarray(sample_tokens(
                lp, self._keys(1, seed=ks), np.full(1, 1.5, np.float32),
                np.full(1, 3, np.int32), np.ones(1, np.float32)))[0])
            assert tok in allowed

    def test_same_key_same_token_key_dependence_exists(self):
        lp = self._logp(1, seed=3)
        # hot temperature -> near-uniform draw, so distinct keys must
        # surface distinct tokens within a handful of seeds
        args = (np.full(1, 8.0, np.float32), np.zeros(1, np.int32),
                np.ones(1, np.float32))
        a = np.asarray(sample_tokens(lp, self._keys(1, seed=5), *args))
        b = np.asarray(sample_tokens(lp, self._keys(1, seed=5), *args))
        np.testing.assert_array_equal(a, b)
        draws = {int(np.asarray(sample_tokens(
            lp, self._keys(1, seed=s), *args))[0]) for s in range(25)}
        assert len(draws) > 1          # the key actually drives the draw

    def test_row_independent_of_batch_composition(self):
        """The continuous-batching determinism primitive: a row's draw
        depends only on its own (logp, key, knobs), never on who else is
        in the slot batch."""
        lp = self._logp(3, seed=4)
        keys = self._keys(3, seed=6)
        t = np.asarray([0.8, 1.2, 0.0], np.float32)
        k = np.asarray([0, 5, 0], np.int32)
        p = np.asarray([0.9, 1.0, 1.0], np.float32)
        full = np.asarray(sample_tokens(lp, keys, t, k, p))
        for i in range(3):
            alone = np.asarray(sample_tokens(
                lp[i:i + 1], keys[i:i + 1], t[i:i + 1], k[i:i + 1],
                p[i:i + 1]))
            assert int(alone[0]) == int(full[i]), f"row {i}"


# ------------------------------------------------------------------- engine
class TestGenerationEngine:
    def test_greedy_matches_naive_reforward(self, lm):
        eng = GenerationEngine.for_model(
            lm, GenerationConfig(max_slots=4, max_seq=32))
        try:
            eng.warmup()
            prompt = [3, 1, 4, 1, 5]
            res = eng.generate(prompt, max_new_tokens=8)
            assert res.tokens == naive_greedy(lm, prompt, 8)
            assert res.finish == "length"
            assert res.prompt_len == 5
            assert eng.steady_recompiles == 0
        finally:
            eng.shutdown()

    def test_two_programs_zero_recompiles_across_mixed_workload(self, lm):
        """The acceptance counter-check: after warmup the ENTIRE mixed
        workload — ragged prompt lengths spanning every bucket,
        stochastic + greedy requests, mid-flight joins, EOS and budget
        completions — executes on the warmed program set.  Verified two
        ways: the engine's own post-warmup trace counter AND the global
        per-fn compile counter deltas."""
        eng = GenerationEngine.for_model(
            lm, GenerationConfig(max_slots=3, max_seq=32, queue_limit=64))
        reg = default_registry()
        try:
            warmed = eng.warmup()
            # exactly two steady-state program KINDS: one prefill per
            # bucket (8/16/32) plus ONE decode over the full slot batch
            assert warmed == len(eng.buckets) + 1
            pf0, dec0 = compiles("paged_prefill"), compiles("paged_decode")
            steady0 = reg.get("serving_steady_recompiles_total")
            steady0 = 0.0 if steady0 is None else steady0.value
            rng = np.random.default_rng(0)
            reqs = []
            for i, plen in enumerate([1, 5, 8, 9, 16, 17, 2, 26]):
                reqs.append(eng.submit(
                    rng.integers(0, VOCAB, plen).tolist(),
                    max_new_tokens=4 + (i % 3),
                    temperature=0.0 if i % 2 else 0.9,
                    top_k=0 if i % 3 else 5, seed=100 + i,
                    eos_id=int(rng.integers(0, VOCAB)) if i == 3 else None))
                if i == 4:          # stagger: later submits join mid-run
                    wait_until(lambda: any(r.out_tokens for r in reqs))
            results = [r.future.result(timeout=60) for r in reqs]
            assert all(r.finish in ("eos", "length") for r in results)
            assert eng.steady_recompiles == 0
            assert compiles("paged_prefill") == pf0
            assert compiles("paged_decode") == dec0
            steady = reg.get("serving_steady_recompiles_total")
            assert (0.0 if steady is None else steady.value) == steady0
            assert eng.tokens_generated == sum(len(r.tokens)
                                               for r in results)
        finally:
            eng.shutdown()

    def test_late_join_matches_solo_run_bit_level(self, lm):
        """The continuous-batching acceptance: request R streamed into a
        RUNNING decode batch produces exactly the tokens R produces on an
        idle engine — and the running batch never restarted."""
        eng = GenerationEngine.for_model(
            lm, GenerationConfig(max_slots=4, max_seq=32))
        try:
            eng.warmup()
            kw = dict(max_new_tokens=10, temperature=0.85, top_k=6,
                      top_p=0.95, seed=424242)
            prompt = [2, 7, 1, 8]
            solo = eng.generate(prompt, **kw)

            long_req = eng.submit([5, 3], max_new_tokens=26,
                                  temperature=0.7, seed=1)
            assert wait_until(lambda: len(long_req.out_tokens) >= 3)
            assert not long_req.future.done()   # genuinely mid-flight
            steps_before = eng.decode_steps
            joined = eng.submit(prompt, **kw)
            late = joined.future.result(timeout=60)
            long_res = long_req.future.result(timeout=60)
            assert late.tokens == solo.tokens   # bit-level determinism
            assert long_res.finish == "length"
            # the running batch kept stepping; nothing restarted
            assert eng.decode_steps > steps_before
            assert eng.steady_recompiles == 0
        finally:
            eng.shutdown()

    def test_eos_vacates_slot_mid_flight_and_trail_records_it(self, lm):
        eng = GenerationEngine.for_model(
            lm, GenerationConfig(max_slots=2, max_seq=32))
        try:
            eng.warmup()
            prompt = [3, 1, 4, 1, 5]
            ref = naive_greedy(lm, prompt, 8)
            eos = ref[3]                   # stop at its first occurrence
            res = eng.generate(prompt, max_new_tokens=8, eos_id=eos)
            assert res.finish == "eos"
            assert res.tokens == ref[:ref.index(eos) + 1]
            assert wait_until(lambda: eng.ring.free_slots == 2)
            events = [(e["event"], e["reason"]) if "reason" in e
                      else e["event"] for e in eng.ring.trail()]
            assert "install" in events
            assert ("vacate", "eos") in events
        finally:
            eng.shutdown()

    def test_stream_yields_per_token_events_and_cancel_vacates(self, lm):
        eng = GenerationEngine.for_model(
            lm, GenerationConfig(max_slots=1, max_seq=32))
        try:
            eng.warmup()
            events = list(eng.stream([4, 2], max_new_tokens=5))
            assert [e["index"] for e in events[:-1]] == list(range(5))
            assert all("token" in e and "model_version" in e
                       for e in events[:-1])
            assert events[-1]["done"] and events[-1]["finish"] == "length"
            assert events[-1]["tokens"] == [e["token"] for e in events[:-1]]
            # abandoning the iterator cancels the request -> slot vacates
            it = eng.stream([1, 2, 3], max_new_tokens=28)
            first = next(it)
            assert "token" in first
            it.close()
            assert wait_until(lambda: eng.ring.free_slots == 1)
        finally:
            eng.shutdown()

    def test_admission_sheds_no_slots_with_metric_and_retry_after(self, lm):
        reg = MetricsRegistry()
        # start=False: no decode thread, so the join queue provably holds
        eng = GenerationEngine.for_model(
            lm, GenerationConfig(max_slots=1, queue_limit=2, max_seq=32),
            registry=reg, start=False)
        try:
            eng.submit([1], max_new_tokens=4)
            eng.submit([2], max_new_tokens=4)
            assert eng.ready() is False     # queue at its shed limit
            with pytest.raises(ShedError) as ei:
                eng.submit([3], max_new_tokens=4)
            assert ei.value.status == 429
            assert ei.value.retry_after_s > 0
            shed = reg.get("serving_shed_total")
            assert shed is not None and \
                shed.labels("no_slots", "-").value == 1
        finally:
            eng.shutdown()

    def test_unready_sheds_503_and_invalid_inputs_400_class(self, lm):
        reg = MetricsRegistry()
        eng = GenerationEngine(lambda: None, GenerationConfig(max_seq=32),
                               registry=reg, start=False)
        try:
            with pytest.raises(ShedError) as ei:
                eng.submit([1])
            assert ei.value.status == 503
            assert reg.get("serving_shed_total") \
                .labels("unready", "-").value == 1
        finally:
            eng.shutdown()
        eng = GenerationEngine.for_model(
            lm, GenerationConfig(max_seq=32), start=False)
        try:
            with pytest.raises(InvalidInputError):
                eng.submit([])
            with pytest.raises(InvalidInputError):
                eng.submit([1], max_new_tokens=0)
            with pytest.raises(InvalidInputError):
                eng.submit([1] * 30, max_new_tokens=8)   # 38 > max_seq 32
        finally:
            eng.shutdown()

    def test_decode_slo_breach_flips_readiness(self, lm):
        eng = GenerationEngine.for_model(
            lm, GenerationConfig(max_slots=2, max_seq=32,
                                 itl_slo_ms=1e-7, slo_min_samples=4))
        try:
            eng.warmup()
            assert eng.ready() is True      # no samples yet: SLO vacuous
            eng.generate([1, 2], max_new_tokens=8)
            assert eng.decode_slo_ok() is False
            assert eng.ready() is False
            assert eng.status()["decode_slo_ok"] is False
            assert eng.status()["itl_p99_ms"] > 0
        finally:
            eng.shutdown()

    def test_decode_exception_dumps_occupancy_trail_and_loop_survives(
            self, lm, tmp_path, monkeypatch):
        from deeplearning4j_tpu.observability import (FlightRecorder,
                                                      load_dump)
        from deeplearning4j_tpu.observability.recorder import \
            set_flight_recorder
        rec = FlightRecorder(directory=str(tmp_path),
                             min_dump_interval_s=0.0)
        prev = set_flight_recorder(rec)
        orig = lm._get_jitted
        fail = threading.Event()
        fail.set()

        def patched(kind):
            fn = orig(kind)
            if kind == "paged_decode" and fail.is_set():
                def boom(*a, **k):
                    raise RuntimeError("injected decode fault")
                return boom
            return fn

        monkeypatch.setattr(lm, "_get_jitted", patched)
        eng = GenerationEngine.for_model(
            lm, GenerationConfig(max_slots=2, max_seq=32))
        try:
            req = eng.submit([1, 2, 3], max_new_tokens=6, seed=9)
            with pytest.raises(RuntimeError, match="injected decode"):
                req.future.result(timeout=60)
            assert wait_until(lambda: rec.dumps)
            payload = load_dump(rec.dumps[0])     # checksum-verified
            assert payload["reason"] == "decode_exception"
            errs = [r for r in payload["channels"]["decode"]
                    if r["type"] == "decode_error"]
            assert errs
            occ = errs[0]["occupancy"]
            assert occ["active"] == 1 and occ["max_slots"] == 2
            assert any(t["event"] == "install" and t["request"] == req.id
                       for t in occ["trail"])
            assert req.id in " ".join(occ["occupants"].values())
            # the decode loop survived the fault: clear the injection and
            # the next request serves normally from a clean ring
            fail.clear()
            res = eng.generate([1, 2, 3], max_new_tokens=4, timeout=60)
            assert res.finish == "length"
            assert eng.ring.active_slots == 0
        finally:
            set_flight_recorder(prev)
            eng.shutdown()

    def test_refuses_non_generatable_stacks(self):
        from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                        NeuralNetConfiguration)
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        conf = (NeuralNetConfiguration.builder().seed(1).list()
                .layer(DenseLayer(n_out=4, activation="relu"))
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(4)).build())
        ff = MultiLayerNetwork(conf).init()
        eng = GenerationEngine.for_model(ff, GenerationConfig(max_seq=16),
                                         start=False)
        try:
            with pytest.raises(ValueError, match="carry-capable"):
                eng.warmup()
        finally:
            eng.shutdown()
        # a LIVE engine over the same stack must fail the submitted
        # request with the real reason — not drop it into a silent
        # client timeout (the popped request must never vanish)
        eng = GenerationEngine.for_model(ff, GenerationConfig(max_seq=16))
        try:
            req = eng.submit([1, 2], max_new_tokens=2)
            with pytest.raises(ValueError, match="carry-capable"):
                req.future.result(timeout=30)
        finally:
            eng.shutdown()

    def test_fresh_carry_capacity_forwarded_or_refused_loudly(self):
        """The engine sizes KV caches by max_seq, not the layer's conf
        default: wrappers must forward max_len (FrozenLayer does), and a
        carry layer that silently ignores it is refused instead of
        clamping writes past its capacity into wrong tokens."""
        import jax.numpy as jnp
        from deeplearning4j_tpu.generation.programs import _fresh_carry
        from deeplearning4j_tpu.nn.layers.attention import TransformerBlock
        from deeplearning4j_tpu.nn.layers.misc import FrozenLayer
        frozen = FrozenLayer(underlying=TransformerBlock(
            n_in=8, n_heads=2, causal=True, attn_impl="reference"))
        assert frozen.HAS_CARRY
        carry = _fresh_carry(frozen, 2, 7)
        assert carry["k"].shape[2] == 7          # max_len, not conf

        class LegacyKV:
            def init_carry(self, batch, dtype=jnp.float32):
                return {"k": jnp.zeros((batch, 2, 512, 4)),
                        "pos": jnp.zeros((), jnp.int32)}

        with pytest.raises(ValueError, match="ignored max_len"):
            _fresh_carry(LegacyKV(), 2, 64)

    def test_rewarm_during_active_decode_never_touches_live_kv(self, lm):
        """An operator re-warm while sequences are decoding must trace
        against scratch buffers: slot 0's live KV/pos stay untouched and
        the stream still matches the greedy oracle exactly."""
        eng = GenerationEngine.for_model(
            lm, GenerationConfig(max_slots=2, max_seq=32))
        try:
            eng.warmup()
            prompt = [3, 1, 4, 1]
            req = eng.submit(prompt, max_new_tokens=14)
            assert wait_until(lambda: len(req.out_tokens) >= 2)
            eng.warmup()                       # mid-flight re-warm
            res = req.future.result(timeout=60)
            assert res.tokens == naive_greedy(lm, prompt, 14)
        finally:
            eng.shutdown()

    def test_non_integer_prompt_is_invalid_input_not_500_class(self, lm):
        eng = GenerationEngine.for_model(
            lm, GenerationConfig(max_seq=32), start=False)
        try:
            with pytest.raises(InvalidInputError, match="integer token"):
                eng.submit(["a", "b"])
        finally:
            eng.shutdown()

    def test_generate_timeout_cancels_and_frees_the_slot(self, lm):
        eng = GenerationEngine.for_model(
            lm, GenerationConfig(max_slots=1, max_seq=32), start=False)
        try:
            with pytest.raises(FuturesTimeout):
                eng.generate([1, 2], max_new_tokens=4, timeout=0.05)
            # the abandoned request is cancelled: once the (late-started)
            # decode loop picks it up, it vacates instead of decoding
            eng._thread.start()
            assert wait_until(lambda: eng._pending.qsize() == 0)
            assert eng.ring is None or eng.ring.active_slots == 0
        finally:
            eng.shutdown()


# -------------------------------------------------- serving-tier integration
class TestServingIntegration:
    def test_generate_route_blocking_streaming_and_health(self, lm):
        from deeplearning4j_tpu.serving import (GenerationClient,
                                                ServingServer)
        server = ServingServer(
            lm, max_batch_size=4,
            generation=GenerationConfig(max_slots=2, max_seq=32)).start()
        try:
            client = GenerationClient(f"http://127.0.0.1:{server.port}",
                                      timeout=60)
            prompt = [3, 1, 4]
            expect = naive_greedy(lm, prompt, 6)
            body = client.generate(prompt, max_new_tokens=6)
            assert body["tokens"] == expect
            assert body["finish"] == "length"
            assert body["model_versions"] == [1] * 6
            # streaming: one NDJSON event per token, then the done record
            events = list(client.stream(prompt, max_new_tokens=6))
            assert [e["token"] for e in events[:-1]] == expect
            assert [e["index"] for e in events[:-1]] == list(range(6))
            assert events[-1]["done"] and events[-1]["tokens"] == expect
            # /health carries the generation readiness block
            h = client.get("/health")
            assert h["ready"] is True
            assert h["generation"]["ready"] is True
            assert h["generation"]["max_slots"] == 2
            assert h["generation"]["steady_recompiles"] == 0
            assert server.engine.stats()["generation"]["warm"] is True
            # bad requests map to 400-class, not 500
            import urllib.error
            with pytest.raises(urllib.error.HTTPError) as ei:
                client.generate([], max_new_tokens=2)
            assert ei.value.code == 400
            # client-shaped garbage is 400-class too — it must never
            # charge the server's failure circuit as a 500
            with pytest.raises(urllib.error.HTTPError) as ei:
                client.post("/generate", {"tokens": ["x", "y"]})
            assert ei.value.code == 400
            assert client.get("/health")["ready"] is True
        finally:
            server.stop()

    def test_generate_route_404_when_generation_disabled(self, lm):
        from deeplearning4j_tpu.serving import (GenerationClient,
                                                ServingServer)
        import urllib.error
        server = ServingServer(lm, max_batch_size=4).start()
        try:
            client = GenerationClient(f"http://127.0.0.1:{server.port}",
                                      timeout=60)
            assert client.get("/health")["generation"] is None
            with pytest.raises(urllib.error.HTTPError) as ei:
                client.generate([1, 2], max_new_tokens=2)
            assert ei.value.code == 404
        finally:
            server.stop()

    def test_hot_swap_during_active_decode_migrates_without_mixing(
            self, lm):
        """ISSUE 11 hot-swap acceptance, the PR 8 contract extended to
        the decode path: a weight swap while streaming clients hold
        active slots must (a) never mix weight versions inside one
        sequence — every token matches exactly the weights of the
        version it reports, verified against per-version greedy oracles
        on the request's own history — (b) never move versions
        backwards, and (c) cost zero steady-state recompiles (same
        topology: the programs are value-keyed on conf, not params)."""
        import jax
        from deeplearning4j_tpu.serving import (GenerationClient,
                                                ServingServer)
        net_b = lm.clone()
        net_b.params = jax.tree_util.tree_map(lambda a: a * 1.07,
                                              net_b.params)
        server = ServingServer(
            lm, max_batch_size=4,
            generation=GenerationConfig(max_slots=4, max_seq=32)).start()
        gen = server.engine.generation
        prompts = [[3, 1, 4, 1], [9, 2, 6], [5, 3, 5, 8, 9]]
        streams, failures = [[] for _ in prompts], []

        def client_loop(i):
            client = GenerationClient(f"http://127.0.0.1:{server.port}",
                                      timeout=120)
            try:
                for ev in client.stream(prompts[i], max_new_tokens=20):
                    if "error" in ev:
                        failures.append(ev["error"])
                        return
                    if not ev.get("done"):
                        streams[i].append((ev["token"],
                                           ev["model_version"]))
            except Exception as e:       # noqa: BLE001 - recorded, asserted
                failures.append(repr(e))

        threads = [threading.Thread(target=client_loop, args=(i,))
                   for i in range(len(prompts))]
        try:
            for t in threads:
                t.start()
            # swap once every stream is genuinely mid-decode
            assert wait_until(
                lambda: all(len(s) >= 2 for s in streams), timeout_s=60)
            assert server.engine.hot_swap(net_b) == 2
            for t in threads:
                t.join(timeout=120)
            assert failures == []
            assert gen.steady_recompiles == 0     # same-topology swap
            mixed_seen = 0
            for i, stream in enumerate(streams):
                toks = [t for t, _ in stream]
                vers = [v for _, v in stream]
                assert len(toks) == 20
                assert vers == sorted(vers)       # never moves backwards
                k = vers.index(2) if 2 in vers else len(toks)
                if 0 < k < len(toks):
                    mixed_seen += 1
                # v1-era tokens match net_a's greedy oracle, v2-era
                # tokens match net_b's continued from the v1 history —
                # exactly "no sequence mixes weights in its KV cache"
                assert toks[:k] == naive_greedy(lm, prompts[i], k)
                if k < len(toks):
                    assert toks[k:] == naive_greedy(
                        net_b, prompts[i] + toks[:k], len(toks) - k)
            assert mixed_seen >= 1    # the swap really landed mid-flight
            h = GenerationClient(f"http://127.0.0.1:{server.port}",
                                 timeout=60).get("/health")
            assert h["model_version"] == 2
            assert h["generation"]["ready"] is True
        finally:
            server.stop()

    def test_inference_server_attach_generation_readiness(self, lm):
        from deeplearning4j_tpu.serving import (InferenceClient,
                                                InferenceServer)
        gen = GenerationEngine.for_model(
            lm, GenerationConfig(max_slots=1, queue_limit=1, max_seq=32),
            start=False)
        server = InferenceServer(lm).attach_generation(gen).start()
        try:
            client = InferenceClient(f"http://127.0.0.1:{server.port}",
                                     timeout=60)
            h = client.get("/health")
            assert h["ready"] is True and h["generation"]["ready"] is True
            # saturate the (never-drained) join queue: generation
            # unreadiness must flip the whole server's readiness
            gen.submit([1], max_new_tokens=4)
            h = client.get("/health")
            assert h["generation"]["ready"] is False
            assert h["ready"] is False and h["status"] == "unready"
        finally:
            server.stop()
            gen.shutdown()


# ------------------------------------------------------- health integration
def test_health_monitor_ttft_and_itl_p99_detectors():
    """The decode tier's latency signals ride the PR 10 monitor: each
    stream has its own sliding-window p99 detector with its own target,
    so prefill pressure (TTFT) and decode pressure (ITL) page
    independently."""
    from deeplearning4j_tpu.observability.health import (HealthConfig,
                                                         HealthMonitor)
    cfg = HealthConfig(ttft_p99_target_ms=50.0, itl_p99_target_ms=5.0,
                       serving_min_samples=8)
    mon = HealthMonitor(config=cfg, registry=MetricsRegistry())
    # healthy: both streams inside their targets -> no detections
    for _ in range(16):
        assert mon.observe_generation(ttft_s=0.01, itl_s=0.001) == []
    # TTFT breaches alone: the ITL stream stays green
    dets = []
    for _ in range(16):
        dets += mon.observe_generation(ttft_s=0.2)
    assert any(d.kind == "generation_ttft_p99" for d in dets)
    assert not any(d.kind == "generation_itl_p99" for d in dets)
    assert mon.status()["state"] == "degraded"
    # ITL breaches independently
    mon2 = HealthMonitor(config=cfg, registry=MetricsRegistry())
    dets = []
    for _ in range(16):
        dets += mon2.observe_generation(itl_s=0.05)
    assert any(d.kind == "generation_itl_p99" for d in dets)
