"""ZeRO-3 sharded SPMD training (ISSUE 12): exact parity with the
replicated path, the 1/dp layout rules, one trace across mesh sizes,
cross-topology checkpoint resharding, and the multi-process device_put
placement fallback.

The parity tests are BIT-FOR-BIT: at a fixed global batch on the same
mesh, the sharded step (reduce-scatter grads, shard-local update,
XLA-inserted forward all-gather) computes the identical program to the
replicated step (dense all-reduce) — GSPMD derives one from the other
purely from the argument shardings, reducing in the same order.  The
one boundary: a TINY sharded contracting dim can make GSPMD prefer
partial-compute + all-reduce over gather-first, which reassociates the
reduction — pinned at reassociation tolerance in its own test below.
"""
import hashlib
import os
import shutil

import numpy as np
import pytest

import jax

from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                NeuralNetConfiguration)
from deeplearning4j_tpu.faulttolerance.checkpoint import (
    CheckpointManager, CorruptCheckpointError)
from deeplearning4j_tpu.nn.conf.updaters import Adam
from deeplearning4j_tpu.nn.layers.feedforward import (DenseLayer,
                                                      OutputLayer)
from deeplearning4j_tpu.observability.registry import default_registry
from deeplearning4j_tpu.parallel import (ParallelWrapper, ShardedTrainer,
                                         make_mesh, per_device_param_bytes,
                                         param_bytes, shard_params,
                                         zero3_spec)
from deeplearning4j_tpu.parallel.mesh import DATA_AXIS, place_sharded

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices")


def mlp(seed=19, hidden=64, features=16, classes=8, lr=0.02,
        precision=None):
    b = (NeuralNetConfiguration.builder().seed(seed)
         .updater(Adam(learning_rate=lr)))
    if precision is not None:
        b = b.precision(precision)
    lb = b.list()
    lb.layer(DenseLayer(n_out=hidden, activation="tanh"))
    lb.layer(DenseLayer(n_out=hidden, activation="tanh"))
    lb.layer(OutputLayer(n_out=classes, activation="softmax",
                         loss="mcxent"))
    conf = lb.set_input_type(InputType.feed_forward(features)).build()
    return MultiLayerNetwork(conf).init()


def batch(n=64, features=16, classes=8, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, features)).astype(np.float32)
    y = np.eye(classes, dtype=np.float32)[rng.integers(0, classes, n)]
    return x, y


def leaves(tree):
    return jax.tree_util.tree_leaves(tree)


def digests(params):
    out = {}
    for lname in sorted(params):
        for pname in sorted(params[lname]):
            a = np.ascontiguousarray(np.array(params[lname][pname]))
            out[f"{lname}/{pname}"] = hashlib.sha256(a.tobytes()).hexdigest()
    return out


def compiles(fn="train_step"):
    c = default_registry().get("training_compile_total")
    return 0.0 if c is None else c.labels(fn).value


# ------------------------------------------------------------- layout rules
def test_zero3_spec_rules():
    from jax.sharding import PartitionSpec as P
    # first axis divisible by dp shards; earlier indivisible axes skip
    assert zero3_spec((16, 8), 8, 0) == P(DATA_AXIS, None)
    assert zero3_spec((6, 16), 8, 0) == P(None, DATA_AXIS)
    assert zero3_spec((32,), 8, 0) == P(DATA_AXIS)
    # nothing divisible -> replicate; sub-threshold -> replicate
    assert zero3_spec((7, 9), 8, 0) == P()
    assert zero3_spec((16, 8), 8, 1_000_000) == P()
    # dp=1: sharding is meaningless
    assert zero3_spec((16, 8), 1, 0) == P()


def test_sharded_trainer_layout_and_bytes():
    net = mlp(seed=3)
    mesh = make_mesh(dp=8)
    st = ShardedTrainer(net, mesh, min_shard_size=0)
    specs = {str(l.sharding.spec) for l in leaves(net.params)}
    assert specs == {"PartitionSpec('data',)", "PartitionSpec('data', None)"}
    # updater mirrors (Adam mu/nu) carry the SAME layout as their params
    opt_specs = {str(l.sharding.spec) for l in leaves(net.opt_state)
                 if getattr(l, "ndim", 0) > 0}
    assert "PartitionSpec('data', None)" in opt_specs
    # the memory win: every leaf divisible -> exactly 1/8 per device
    assert per_device_param_bytes(net.params) * 8 == \
        param_bytes(net.params)
    assert st.per_device_param_bytes() == per_device_param_bytes(net.params)


def test_min_shard_size_replicates_small_leaves():
    net = mlp(seed=4, hidden=64)
    # threshold above every leaf size: everything replicates (and the
    # trainer degrades to the replicated wrapper's layout)
    ShardedTrainer(net, make_mesh(dp=8), min_shard_size=1 << 20)
    specs = {str(l.sharding.spec) for l in leaves(net.params)}
    assert specs == {"PartitionSpec()"}
    assert per_device_param_bytes(net.params) == param_bytes(net.params)


def test_make_mesh_oversubscription_is_a_clear_error():
    with pytest.raises(ValueError, match="oversubscribes"):
        make_mesh(dp=16)
    with pytest.raises(ValueError, match="oversubscribes"):
        make_mesh(dp=4, tp=2, sp=2)  # 16 > 8
    # an explicit dp smaller than the device count takes a sub-mesh
    assert make_mesh(dp=2).shape[DATA_AXIS] == 2


# ----------------------------------------------------------------- parity
@pytest.mark.parametrize("dp", [2, 4, 8])
def test_sharded_step_matches_replicated_bitwise(dp):
    """The acceptance gate: sharded step == replicated step BIT-FOR-BIT
    on the same data at a fixed global batch, for any dp size."""
    x, y = batch()
    net_r, net_s = mlp(seed=21), mlp(seed=21)
    mesh = make_mesh(dp=dp)
    pw = ParallelWrapper(net_r, mesh)
    st = ShardedTrainer(net_s, mesh, min_shard_size=0)
    for _ in range(4):
        pw.fit(x, y)
        st.fit(x, y)
    assert net_r.get_score() == net_s.get_score()
    for a, b in zip(leaves(net_r.params), leaves(net_s.params)):
        np.testing.assert_array_equal(np.array(a), np.array(b))
    # updater state agrees too (the shard-local update is the full update)
    for a, b in zip(leaves(net_r.opt_state), leaves(net_s.opt_state)):
        np.testing.assert_array_equal(np.array(a), np.array(b))


def test_sharded_masters_bf16_matches_replicated():
    """PrecisionPolicy composition: with bf16 compute the sharded params
    ARE the f32 masters — sharded-master training is bitwise the
    replicated mixed-precision run, and the masters never downcast."""
    x, y = batch(seed=5)
    net_r, net_s = mlp(seed=23, precision="bfloat16"), \
        mlp(seed=23, precision="bfloat16")
    mesh = make_mesh(dp=8)
    pw = ParallelWrapper(net_r, mesh)
    st = ShardedTrainer(net_s, mesh, min_shard_size=0)
    for _ in range(3):
        pw.fit(x, y)
        st.fit(x, y)
    for a, b in zip(leaves(net_r.params), leaves(net_s.params)):
        assert a.dtype == b.dtype
        assert a.dtype != np.dtype("bfloat16")   # masters stay full precision
        np.testing.assert_array_equal(np.array(a), np.array(b))
    assert any("data" in str(l.sharding.spec)
               for l in leaves(net_s.params))


def test_parity_boundary_tiny_contraction_is_reassociation_tolerance():
    """The parity contract's boundary, pinned so nobody 'fixes' it into a
    flake: bitwise equality holds when GSPMD all-gathers the sharded
    params before the matmul (its choice for every representative shape
    — the tests above).  For a TINY sharded contracting dim (features=4
    here, W0 is (4, h)) GSPMD instead partial-computes and all-reduces
    the activations, which reassociates the reduction: parity is then
    ~1e-6-relative (f32) — the same noise class as changing dp in any
    data-parallel run — and must still hold to tight tolerance."""
    rng = np.random.default_rng(2)
    x = rng.standard_normal((48, 4)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 48)]
    net_r, net_s = mlp(seed=43, features=4, classes=3), \
        mlp(seed=43, features=4, classes=3)
    mesh = make_mesh(dp=4)
    pw = ParallelWrapper(net_r, mesh)
    st = ShardedTrainer(net_s, mesh, min_shard_size=0)
    for _ in range(4):
        pw.fit(x, y)
        st.fit(x, y)
    for a, b in zip(leaves(net_r.params), leaves(net_s.params)):
        np.testing.assert_allclose(np.array(a), np.array(b),
                                   rtol=2e-5, atol=2e-6)


# ----------------------------------------------------------- compile budget
def test_one_trace_serves_every_mesh_size():
    """dp=2 and dp=4 runs (and the replicated wrapper) share ONE trace of
    the train step: sharding lives in the arguments, not the jaxpr, so
    the process-global trace cache serves every mesh size from a single
    Python trace (each dp still lowers its own executable)."""
    x, y = batch(seed=7)
    before = compiles()
    # hidden=72 keeps this topology unique to this test: the counter
    # delta below must not be absorbed by another test's cached trace
    nets = [mlp(seed=29, hidden=72) for _ in range(3)]
    ShardedTrainer(nets[0], make_mesh(dp=2), min_shard_size=0).fit(x, y)
    ShardedTrainer(nets[1], make_mesh(dp=4), min_shard_size=0).fit(x, y)
    ParallelWrapper(nets[2], make_mesh(dp=8)).fit(x, y)
    assert compiles() - before == 1


# ------------------------------------------------- checkpoint resharding
def _fit_and_save(tmp_path, dp=4, steps=3):
    x, y = batch(seed=11)
    net = mlp(seed=31)
    st = ShardedTrainer(net, make_mesh(dp=dp), min_shard_size=0)
    for _ in range(steps):
        st.fit(x, y)
    mgr = CheckpointManager(str(tmp_path / "store"), background=False)
    path = mgr.save_sharded(net, cursor={"fit_epoch": 2, "batch_seq": 5},
                            step=steps)
    return net, mgr, path, (x, y)


def test_cross_topology_roundtrip_digests_exact(tmp_path):
    """Save on a dp=4 mesh, restore onto dp=2 AND dp=8: param digests
    exactly equal (reassembly + re-placement move bytes, never
    arithmetic), cursor intact, and training continues on the new mesh."""
    net, mgr, path, (x, y) = _fit_and_save(tmp_path, dp=4)
    want = digests(net.params)
    opt_want = [np.array(l) for l in jax.tree_util.tree_leaves(
        net.opt_state)]
    for dp in (2, 8):
        net2, state = mgr.restore_sharded(mesh=make_mesh(dp=dp),
                                          min_shard_size=0)
        assert digests(net2.params) == want
        assert state["cursor"] == {"fit_epoch": 2, "batch_seq": 5}
        assert net2.iteration == net.iteration
        # updater state reshards exactly too
        for a, b in zip(opt_want,
                        jax.tree_util.tree_leaves(net2.opt_state)):
            np.testing.assert_array_equal(a, np.array(b))
        # the restored net is live: another sharded step on the NEW mesh
        st2 = ShardedTrainer(net2, make_mesh(dp=dp), min_shard_size=0)
        st2.fit(x, y)
        assert np.isfinite(net2.get_score())


def test_restore_sharded_into_existing_net_and_rng(tmp_path):
    net, mgr, path, _ = _fit_and_save(tmp_path)
    target = mlp(seed=31)
    mgr.restore_sharded(path, net=target, mesh=None)
    assert digests(target.params) == digests(net.params)
    # RNG restored: the next key draw matches the saved net's
    a = jax.random.split(net._rng)[1]
    b = jax.random.split(target._rng)[1]
    np.testing.assert_array_equal(np.array(a), np.array(b))


def test_corrupt_shard_refuses(tmp_path):
    _, mgr, path, _ = _fit_and_save(tmp_path)
    shard = next(f for f in os.listdir(path) if f.endswith(".npz"))
    with open(os.path.join(path, shard), "r+b") as f:
        f.seek(40)
        f.write(b"\xde\xad\xbe\xef")
    with pytest.raises(CorruptCheckpointError, match="checksum"):
        mgr.restore_sharded(path, mesh=make_mesh(dp=2))


def test_missing_shard_file_refuses(tmp_path):
    _, mgr, path, _ = _fit_and_save(tmp_path)
    shard = next(f for f in os.listdir(path) if f.endswith(".npz"))
    os.remove(os.path.join(path, shard))
    with pytest.raises(CorruptCheckpointError, match="missing"):
        mgr.restore_sharded(path, mesh=make_mesh(dp=2))


def test_multiprocess_save_refuses_without_barrier(tmp_path):
    """A primary-only commit in a multi-process world would record
    process_count shard files in topology.json but write one — a torn
    checkpoint every restore refuses.  save_sharded must refuse up
    front, for the primary too, until the staged-write barrier exists."""
    net = mlp(seed=47)
    ShardedTrainer(net, make_mesh(dp=4), min_shard_size=0)
    mgr = CheckpointManager(str(tmp_path / "store"), background=False)
    with pytest.raises(NotImplementedError, match="barrier"):
        mgr.save_sharded(net, process_index=1, process_count=2)
    with pytest.raises(NotImplementedError, match="barrier"):
        mgr.save_sharded(net, process_index=0, process_count=2)


def test_restore_kind_mismatch_is_a_clear_error(tmp_path):
    net, mgr, path, _ = _fit_and_save(tmp_path)
    # dense restore() on a sharded checkpoint: refuse (the container
    # carries no params — a silent fresh-init restore would be wrong)
    with pytest.raises(ValueError, match="SHARDED"):
        mgr.restore(path)
    # restore_sharded on a dense checkpoint: refuse symmetrically
    dense = CheckpointManager(str(mgr.directory) + "-dense",
                              background=False)
    dense.save(mlp(seed=33), blocking=True)
    with pytest.raises(ValueError, match="not a sharded"):
        dense.restore_sharded(mesh=make_mesh(dp=2))
    shutil.rmtree(dense.directory, ignore_errors=True)


def test_multi_axis_sharded_leaf_refused_at_save():
    """The shard format indexes ONE sharded dim per leaf (ZeRO-3); a
    two-axis partition (a TP param_rule composed with dp) must refuse at
    save time, not dedupe away the second axis and commit a store every
    restore rejects."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from deeplearning4j_tpu.faulttolerance.checkpoint import _leaf_blocks
    mesh = make_mesh(dp=2, tp=2)
    leaf = jax.device_put(np.arange(64.0).reshape(8, 8),
                          NamedSharding(mesh, P("data", "model")))
    with pytest.raises(NotImplementedError, match="sharded over 2 axes"):
        _leaf_blocks(leaf)


def test_restore_into_mismatched_net_leaves_it_untouched(tmp_path):
    """A topology mismatch mid-restore must not leave a caller's live
    net half old-mesh, half new: params swap only after every key
    assembled and validated."""
    net, mgr, path, _ = _fit_and_save(tmp_path)
    other = mlp(seed=51, hidden=32)   # different topology
    before = digests(other.params)
    with pytest.raises(ValueError):
        mgr.restore_sharded(path, net=other, mesh=make_mesh(dp=2))
    assert digests(other.params) == before


def test_save_sharded_honors_save_updater_false(tmp_path):
    """CheckpointManager(save_updater=False) must drop updater state on
    the sharded path too (the dense writer honors it): no opt blocks in
    the store, and a restore leaves the target's fresh opt_state."""
    x, y = batch(seed=17)
    net = mlp(seed=57)
    ShardedTrainer(net, make_mesh(dp=4), min_shard_size=0).fit(x, y)
    mgr = CheckpointManager(str(tmp_path / "store"), background=False,
                            save_updater=False)
    path = mgr.save_sharded(net, step=1)
    import json
    with open(os.path.join(path, "topology.json")) as f:
        topo = json.load(f)
    assert topo["opt"] == []
    net2, _ = mgr.restore_sharded(path, mesh=make_mesh(dp=2),
                                  min_shard_size=0)
    assert digests(net2.params) == digests(net.params)
    # fresh updater state: every non-scalar moment leaf is zeros
    moments = [np.array(l) for l in leaves(net2.opt_state)
               if getattr(l, "ndim", 0) > 0]
    assert moments and all((m == 0).all() for m in moments)


def test_failed_updater_restore_leaves_net_untouched(tmp_path):
    """A restore that fails in the UPDATER section (checkpoint saved
    under a different updater config) must not have swapped params in
    already — the live net stays fully old."""
    net, mgr, path, _ = _fit_and_save(tmp_path)
    # same layer topology, different updater: Sgd has fewer state leaves
    from deeplearning4j_tpu.nn.conf.updaters import Sgd
    b = NeuralNetConfiguration.builder().seed(31).updater(
        Sgd(learning_rate=0.02))
    lb = b.list()
    lb.layer(DenseLayer(n_out=64, activation="tanh"))
    lb.layer(DenseLayer(n_out=64, activation="tanh"))
    lb.layer(OutputLayer(n_out=8, activation="softmax", loss="mcxent"))
    other = MultiLayerNetwork(
        lb.set_input_type(InputType.feed_forward(16)).build()).init()
    before = digests(other.params)
    opt_before = [np.array(l) for l in leaves(other.opt_state)]
    with pytest.raises(ValueError, match="updater state mismatch"):
        mgr.restore_sharded(path, net=other, mesh=make_mesh(dp=2))
    assert digests(other.params) == before
    for a, b_ in zip(opt_before, leaves(other.opt_state)):
        np.testing.assert_array_equal(a, np.array(b_))


def test_sharded_write_fires_chaos_stages(tmp_path):
    """The crash-consistency harness's commit-stage hooks fire in the
    sharded writer too (stage 1 after the container, stage 2 after the
    shard files) — the torn-sharded-store windows stay probeable."""
    net = mlp(seed=53)
    ShardedTrainer(net, make_mesh(dp=4), min_shard_size=0)
    mgr = CheckpointManager(str(tmp_path / "store"), background=False)

    class Chaos:
        stages = []

        def on_commit_stage(self, step, stage):
            self.stages.append((step, stage))

    mgr.chaos = Chaos()
    mgr.save_sharded(net, step=7)
    assert mgr.chaos.stages == [(7, 1), (7, 2)]


# --------------------------------------------- multi-process put fallback
def test_place_sharded_falls_back_per_shard(monkeypatch):
    """The CPU-rig regression (PR 7's note): when ``device_put`` onto a
    NamedSharding is unimplemented, ``ParallelWrapper``/``ShardedTrainer``
    placement must fall back to per-shard device_put +
    ``make_array_from_single_device_arrays`` instead of crashing
    mid-fit."""
    from jax.sharding import Sharding
    real_put = jax.device_put

    def flaky_put(x, device=None, **kw):
        if isinstance(device, Sharding):
            raise RuntimeError(
                "UNIMPLEMENTED: device_put to a multi-process sharding")
        return real_put(x, device, **kw)

    import deeplearning4j_tpu.parallel.mesh as mesh_mod
    monkeypatch.setattr(mesh_mod.jax, "device_put", flaky_put)
    x, y = batch(seed=13)
    net = mlp(seed=37)
    st = ShardedTrainer(net, make_mesh(dp=4), min_shard_size=0)
    st.fit(x, y)
    assert np.isfinite(net.get_score())
    specs = {str(l.sharding.spec) for l in leaves(net.params)}
    assert specs == {"PartitionSpec('data',)", "PartitionSpec('data', None)"}
    # parity holds through the fallback placement too
    net_ref = mlp(seed=37)
    monkeypatch.undo()
    ShardedTrainer(net_ref, make_mesh(dp=4), min_shard_size=0).fit(x, y)
    for a, b in zip(leaves(net_ref.params), leaves(net.params)):
        np.testing.assert_array_equal(np.array(a), np.array(b))


def test_place_sharded_reraises_when_fallback_also_fails(monkeypatch):
    import deeplearning4j_tpu.parallel.mesh as mesh_mod

    def always_fail(x, device=None, **kw):
        raise RuntimeError("UNIMPLEMENTED: no placement at all")

    monkeypatch.setattr(mesh_mod.jax, "device_put", always_fail)
    from jax.sharding import NamedSharding, PartitionSpec as P
    sh = NamedSharding(make_mesh(dp=2), P())
    with pytest.raises(RuntimeError, match="no placement"):
        place_sharded(np.zeros(4), sh)


def test_shard_params_helper_shared_surface():
    """The helper the trainer, the checkpoint reshard path and these
    tests all share: one rule, three consumers."""
    net = mlp(seed=41)
    mesh = make_mesh(dp=8)
    sh = shard_params(mesh, net.params, min_size=0)
    flat = jax.tree_util.tree_leaves_with_path(sh)
    assert flat and all("data" in str(s.spec) for _, s in flat)
    placed = jax.tree_util.tree_map(place_sharded, net.params, sh)
    for a, b in zip(leaves(net.params), leaves(placed)):
        np.testing.assert_array_equal(np.array(a), np.array(b))
