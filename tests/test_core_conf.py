"""Core config system tests: serde round-trip, shape inference, defaults.

Mirrors reference test intent: config JSON round-trip
(MultiLayerConfiguration.toJson/fromJson) and InputType shape inference.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu import (InputType, MultiLayerConfiguration,
                                MultiLayerNetwork, NeuralNetConfiguration)
from deeplearning4j_tpu.nn import activations, losses
from deeplearning4j_tpu.nn.conf.updaters import Adam, Sgd, by_name
from deeplearning4j_tpu.nn.conf.schedules import (ExponentialSchedule,
                                                  StepSchedule)
from deeplearning4j_tpu.nn.layers.feedforward import (DenseLayer, OutputLayer)
from deeplearning4j_tpu.nn.weights import init_weights


def build_conf():
    return (NeuralNetConfiguration.builder()
            .seed(42)
            .updater(Adam(learning_rate=1e-3))
            .weight_init("xavier")
            .l2(1e-4)
            .list()
            .layer(DenseLayer(n_out=20, activation="relu"))
            .layer(DenseLayer(n_out=10, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4))
            .build())


def test_shape_inference_and_defaults():
    conf = build_conf()
    assert conf.layers[0].n_in == 4
    assert conf.layers[1].n_in == 20
    assert conf.layers[2].n_in == 10
    # global default inherited
    assert conf.layers[0].l2 == 1e-4
    assert isinstance(conf.defaults["updater"], Adam)


def test_json_roundtrip():
    conf = build_conf()
    js = conf.to_json()
    conf2 = MultiLayerConfiguration.from_json(js)
    assert len(conf2.layers) == 3
    assert conf2.layers[0].n_in == 4
    assert conf2.layers[2].loss == "mcxent"
    assert conf2.seed == 42
    # round-trip idempotent
    assert conf2.to_json() == js


def test_yaml_roundtrip():
    conf = build_conf()
    y = conf.to_yaml()
    conf2 = MultiLayerConfiguration.from_yaml(y)
    assert conf2.layers[1].n_out == 10


def test_unknown_field_tolerated():
    import json
    conf = build_conf()
    d = json.loads(conf.to_json())
    d["layers"][0]["brand_new_field"] = 123
    conf2 = MultiLayerConfiguration.from_json(json.dumps(d))
    assert conf2.layers[0].n_out == 20


def test_num_params():
    conf = build_conf()
    net = MultiLayerNetwork(conf).init()
    assert net.num_params() == (4 * 20 + 20) + (20 * 10 + 10) + (10 * 3 + 3)


def test_activations_registry():
    x = jnp.linspace(-2, 2, 11)
    for name in activations.names():
        y = activations.get(name)(x)
        assert y.shape == x.shape
        assert bool(jnp.all(jnp.isfinite(y)))
    assert float(activations.get("relu")(jnp.asarray(-1.0))) == 0.0


def test_weight_init_schemes():
    key = jax.random.PRNGKey(0)
    for scheme in ["xavier", "xavier_uniform", "relu", "relu_uniform", "uniform",
                   "lecun_normal", "lecun_uniform", "normal", "zero", "ones",
                   "sigmoid_uniform", "var_scaling_normal_fan_avg"]:
        w = init_weights(key, (50, 40), scheme)
        assert w.shape == (50, 40)
    assert float(jnp.sum(init_weights(key, (5, 5), "zero"))) == 0.0
    ident = init_weights(key, (4, 4), "identity")
    assert np.allclose(np.asarray(ident), np.eye(4))
    # xavier variance approx 2/(fan_in+fan_out)
    w = init_weights(key, (500, 300), "xavier")
    assert abs(float(jnp.var(w)) - 2.0 / 800) < 5e-4


def test_updater_by_name():
    for name in ["sgd", "adam", "adamax", "adadelta", "nesterovs", "nadam",
                 "adagrad", "rmsprop", "none", "amsgrad"]:
        u = by_name(name, learning_rate=0.01)
        tx = u.to_optax()
        assert tx is not None


def test_schedules():
    s = StepSchedule(initial_value=0.1, decay_rate=0.5, step=10)
    assert float(s.value(0)) == pytest.approx(0.1)
    assert float(s.value(10)) == pytest.approx(0.05)
    e = ExponentialSchedule(initial_value=1.0, gamma=0.9)
    assert float(e.value(2)) == pytest.approx(0.81)


def test_losses_registry():
    key = jax.random.PRNGKey(3)
    pre = jax.random.normal(key, (8, 5))
    lab_onehot = jax.nn.one_hot(jnp.arange(8) % 5, 5)
    for name in ["mse", "mae", "xent", "mcxent", "hinge", "squared_hinge",
                 "kl_divergence", "poisson", "cosine_proximity", "mape", "msle"]:
        act = "sigmoid" if name in ("xent",) else "softmax" if name in (
            "mcxent", "kl_divergence") else "sigmoid" if name in ("poisson", "msle") else "identity"
        v = losses.get(name)(lab_onehot, pre, act)
        assert jnp.isfinite(v), name
    # fused mcxent == explicit form
    explicit = float(jnp.mean(-jnp.sum(lab_onehot * jnp.log(jax.nn.softmax(pre)), axis=1)))
    fused = float(losses.get("mcxent")(lab_onehot, pre, "softmax"))
    assert fused == pytest.approx(explicit, rel=1e-5)


def test_input_type():
    it = InputType.convolutional(28, 28, 1)
    assert it.flat_size() == 784
    assert it.shape(32) == (32, 28, 28, 1)
    r = InputType.recurrent(10, 5)
    assert r.shape(4) == (4, 5, 10)
    x = jnp.zeros((2, 28, 28, 3))
    assert InputType.infer(x).kind == "cnn"


def test_global_defaults_reach_wrapped_layers():
    """Review regression: Bidirectional/LastTimeStep wrappers must receive
    network-level defaults (l2, weight_init) on their inner layer."""
    from deeplearning4j_tpu import InputType, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers.feedforward import OutputLayer
    from deeplearning4j_tpu.nn.layers.recurrent import (Bidirectional,
                                                        LastTimeStep, LSTM)
    conf = (NeuralNetConfiguration.builder()
            .seed(0).l2(0.5).weight_init("uniform")
            .list()
            .layer(Bidirectional(fwd=LSTM(n_out=3)))
            .layer(LastTimeStep(underlying=LSTM(n_out=3)))
            .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.recurrent(4, 5))
            .build())
    bi, lts, out = conf.layers
    assert bi.fwd.l2 == 0.5 and bi.fwd.weight_init == "uniform"
    assert lts.underlying.l2 == 0.5
    assert out.l2 == 0.5


def test_loss_weights_scale_per_class():
    """Per-output loss weights (reference LossMCXENT(weights))."""
    import jax.numpy as jnp
    import numpy as np
    from deeplearning4j_tpu.nn.conf.input_type import InputType
    from deeplearning4j_tpu.nn.conf.multi_layer import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.updaters import Sgd
    from deeplearning4j_tpu.nn.layers.feedforward import OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    import pytest

    def net(w=None):
        conf = (NeuralNetConfiguration.builder().seed(0)
                .updater(Sgd(learning_rate=0.1)).list()
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss="mcxent", loss_weights=w))
                .set_input_type(InputType.feed_forward(4)).build())
        return MultiLayerNetwork(conf).init()

    rng = np.random.default_rng(0)
    x = rng.standard_normal((8, 4)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)]
    base = net().score(x=x, y=y)
    doubled = net([2.0, 2.0, 2.0]).score(x=x, y=y)
    assert doubled == pytest.approx(2 * base, rel=1e-5)
    # mismatched width fails fast
    with pytest.raises(ValueError, match="loss weights"):
        net([1.0, 2.0]).score(x=x, y=y)
