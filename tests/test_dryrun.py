"""Hermeticity tests for the driver-graded multi-chip dry run.

The dry run is a CPU-mesh correctness check; it must pass even when the
default backend (the axon-tunneled TPU in production) is poisoned.  Mirrors
the reference's always-runnable local-cluster proof
(dl4j-spark/src/test/java/org/deeplearning4j/spark/BaseSparkTest.java:46 —
``local[N]`` needs no real cluster).
"""
import pytest

from deeplearning4j_tpu.parallel import dryrun


def test_poisoned_default_backend_falls_back_to_subprocess(monkeypatch, capsys):
    """Any in-process failure (e.g. a wedged TPU relay killing an init op)
    must route to the fresh JAX_PLATFORMS=cpu subprocess, not fail the run."""
    calls = []

    def poisoned(n_devices, devices):
        calls.append(n_devices)
        raise RuntimeError("simulated: libtpu client/terminal version mismatch")

    monkeypatch.setattr(dryrun, "_run_in_process", poisoned)
    dryrun.run(2)  # must not raise — subprocess completes the check
    # the stderr notice pins that the poison->fallback transition actually ran
    # (not e.g. a provision_devices shortcut straight to the subprocess).
    assert "falling back to hermetic" in capsys.readouterr().err
    assert calls == [2]


def test_child_never_respawns(monkeypatch):
    """The hermetic subprocess entry point must fail terminally, never
    re-exec (no fork bombs)."""
    spawned = []
    monkeypatch.setattr(dryrun, "_run_in_subprocess",
                        lambda n: spawned.append(n))

    def poisoned(n_devices, devices):
        raise RuntimeError("still broken in child")

    monkeypatch.setattr(dryrun, "_run_in_process", poisoned)
    with pytest.raises(RuntimeError, match="still broken in child"):
        dryrun._child_main(2)
    monkeypatch.setattr(dryrun, "provision_devices", lambda n: None)
    with pytest.raises(RuntimeError, match="could not provision"):
        dryrun._child_main(2)
    assert spawned == []


def test_dryrun_in_process_8_devices():
    """The full driver contract (dp*tp + pipeline/seq + expert steps) on the
    8-device CPU mesh, genuinely in process (no silent subprocess rescue)."""
    devices = dryrun.provision_devices(8)
    assert devices is not None
    dryrun._run_in_process(8, devices)
