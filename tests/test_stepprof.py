"""StepProfiler (ISSUE 17): per-step phase attribution with sampled
device fences, MFU from committed graftaudit cards, memory watermarks vs
the AX008 budgets, and the Chrome-trace / ``/debug/profile`` export
surfaces.

The honesty contracts under test:

* phase sums cover the measured step wall (within 5% on fenced steps);
* UNSAMPLED steps add ZERO host syncs — the PR 16 host-sync sweep
  invariant, asserted by counting ``jax.block_until_ready`` calls and
  pinning the compile counters;
* MFU derives from the committed ``train_step[dense]`` card flops, not
  an analytic formula;
* the trace artifact is checksummed — corruption raises, never loads
  quietly.
"""
import json
import sys
import urllib.error
import urllib.request
from pathlib import Path

import numpy as np
import pytest

REPO_ROOT = Path(__file__).resolve().parents[1]
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))     # for tools.stepprof

from deeplearning4j_tpu.nn.conf.input_type import InputType
from deeplearning4j_tpu.nn.conf.multi_layer import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.updaters import Adam
from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.observability.health import (HealthConfig,
                                                     HealthMonitor)
from deeplearning4j_tpu.observability.profiler import (CHANNEL, PHASES,
                                                       StepProfiler,
                                                       chrome_trace,
                                                       dump_chrome_trace,
                                                       load_chrome_trace,
                                                       phase_summary,
                                                       record_slices,
                                                       resolve_card_flops,
                                                       step_profiler_for,
                                                       stepprof_enabled)
from deeplearning4j_tpu.observability.recorder import (FlightRecorder,
                                                       set_flight_recorder)
from deeplearning4j_tpu.observability.registry import default_registry

CARD_FLOPS = 43446.0          # committed tools/graftaudit/cards value


def tiny_net(seed=42):
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater(Adam(learning_rate=0.02)).list()
            .layer(DenseLayer(n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4)).build())
    return MultiLayerNetwork(conf).init()


def make_batches(n=10, batch=8, seed=0):
    rng = np.random.default_rng(seed)
    return [(rng.standard_normal((batch, 4), dtype=np.float32),
             np.eye(3, dtype=np.float32)[rng.integers(0, 3, batch)])
            for _ in range(n)]


@pytest.fixture
def recorder(tmp_path):
    rec = FlightRecorder(capacity=256, directory=str(tmp_path / "prof"),
                         min_dump_interval_s=0.0)
    prev = set_flight_recorder(rec)
    try:
        yield rec
    finally:
        set_flight_recorder(prev)


def step_records(rec):
    return [r for r in rec.channel(CHANNEL).items() if r["type"] == "step"]


def _compile_counts(reg):
    fam = reg.snapshot().get("training_compile_total")
    if not fam:
        return {}
    return {tuple(sorted(s["labels"].items())): s["value"]
            for s in fam["samples"]}


class TestPhaseAttribution:
    def test_records_phases_and_sampled_coverage(self, recorder,
                                                 monkeypatch):
        monkeypatch.setenv("DL4J_TPU_STEPPROF_SAMPLE", "2")
        net = tiny_net()
        net.fit(iter(make_batches(12)), epochs=1)
        recs = step_records(recorder)
        assert len(recs) == 12
        for r in recs:
            assert set(r["phases"]) == set(PHASES)
            assert r["wall_s"] > 0
        sampled = [r for r in recs if r["sampled"]]
        unsampled = [r for r in recs if not r["sampled"]]
        assert len(sampled) == 6 and unsampled
        # device slice: honest float on fenced steps; on unfenced steps
        # None — unless a later pipeline-aware fence drained the step's
        # in-flight token and attributed its slice ("drained" marker)
        assert all(r["phases"]["device"] > 0 for r in sampled)
        for r in unsampled:
            if r.get("drained"):
                assert r["phases"]["device"] >= 0
            else:
                assert r["phases"]["device"] is None
        # the acceptance contract: on fenced steps the phase breakdown
        # sums to the step wall within 5%
        cov = phase_summary(recs)["sampled_coverage"]
        assert 0.95 <= cov <= 1.05

    def test_unsampled_steps_add_zero_syncs_and_no_retrace(self, recorder,
                                                           monkeypatch):
        import jax
        net = tiny_net()
        batches = make_batches(8)
        # warm: compile outside the counted window
        monkeypatch.setenv("DL4J_TPU_STEPPROF", "0")
        net.fit(iter(batches[:2]), epochs=1)
        reg = default_registry()
        compiles0 = _compile_counts(reg)

        monkeypatch.setenv("DL4J_TPU_STEPPROF", "1")
        monkeypatch.setenv("DL4J_TPU_STEPPROF_SAMPLE", "1000")
        fences = []
        real = jax.block_until_ready
        monkeypatch.setattr(jax, "block_until_ready",
                            lambda x: fences.append(1) or real(x))
        net.fit(iter(batches), epochs=1)
        # every step unsampled -> the profiler never fenced, and the
        # instrumentation did not perturb the traced program
        assert fences == []
        assert _compile_counts(reg) == compiles0
        recs = step_records(recorder)
        assert len(recs) == 8 and not any(r["sampled"] for r in recs)

    def test_fence_cadence_counter_and_depth_gauge(self, recorder,
                                                   monkeypatch):
        import jax
        monkeypatch.setenv("DL4J_TPU_STEPPROF_SAMPLE", "3")
        monkeypatch.setenv("DL4J_TPU_STEPPROF_PROGRAM", "cadence_probe")
        net = tiny_net()
        net.fit(iter(make_batches(2)), epochs=1)      # compile + warm
        fences = []
        real = jax.block_until_ready
        monkeypatch.setattr(jax, "block_until_ready",
                            lambda x: fences.append(1) or real(x))
        net.fit(iter(make_batches(9)), epochs=1)
        assert len(fences) == 3                       # steps 3, 6, 9 only
        reg = default_registry()
        fam = reg.get("stepprof_fences_total")
        assert fam is not None
        assert fam.labels("cadence_probe").value == 3.0
        depth = reg.get("training_dispatch_depth")
        # async dispatch pipelines at least the fenced window's steps
        assert depth is not None and depth.value >= 1

    def test_mfu_from_committed_card_flops(self, recorder, monkeypatch):
        assert resolve_card_flops("train_step[dense]") == CARD_FLOPS
        monkeypatch.setenv("DL4J_TPU_STEPPROF_PROGRAM", "train_step[dense]")
        monkeypatch.setenv("DL4J_TPU_STEPPROF_SAMPLE", "2")
        monkeypatch.setenv("DL4J_TPU_PEAK_FLOPS", "1e12")
        net = tiny_net()
        net.fit(iter(make_batches(8)), epochs=1)
        sampled = [r for r in step_records(recorder) if r["sampled"]]
        assert sampled
        for r in sampled:
            # achieved = card flops / fenced device slice; MFU = achieved
            # over the configured peak — no analytic formula anywhere
            # (rel tolerance: the record's device slice is rounded to
            # 7 decimals, the flops ratio used the raw value)
            assert r["achieved_flops"] == pytest.approx(
                CARD_FLOPS / r["phases"]["device"], rel=0.02)
            assert r["mfu"] == pytest.approx(r["achieved_flops"] / 1e12)
        reg = default_registry()
        fam = reg.get("training_mfu")
        assert fam is not None
        assert fam.labels("train_step[dense]").value == pytest.approx(
            sampled[-1]["mfu"])

    def test_watermark_vs_budget_ratio(self, recorder, tmp_path,
                                       monkeypatch):
        budget = 4096
        budgets = {"programs": {"wm_probe": {"peak_live_bytes": budget}}}
        bpath = tmp_path / "budgets.json"
        bpath.write_text(json.dumps(budgets))
        monkeypatch.setenv("DL4J_TPU_BUDGETS", str(bpath))
        monkeypatch.setenv("DL4J_TPU_STEPPROF_PROGRAM", "wm_probe")
        monkeypatch.setenv("DL4J_TPU_STEPPROF_SAMPLE", "2")
        net = tiny_net()
        net.fit(iter(make_batches(6)), epochs=1)
        sampled = [r for r in step_records(recorder) if r["sampled"]]
        assert sampled
        for r in sampled:
            assert r["live_bytes"] > 0
            # ratio is the observed WATERMARK (max so far) over budget
            # (1e-3 slack: the recorded ratio rounds to 4 decimals)
            assert r["budget_ratio"] >= r["live_bytes"] / budget - 1e-3
        reg = default_registry()
        fam = reg.get("device_live_bytes_budget_ratio")
        assert fam is not None
        assert fam.labels("wm_probe").value >= \
            max(r["live_bytes"] for r in sampled) / budget - 1e-6

    def test_disabled_kills_every_hook(self, recorder, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_STEPPROF", "0")
        assert not stepprof_enabled()
        assert step_profiler_for("train_step") is None
        record_slices("serve", queue_wait_s=0.1)
        net = tiny_net()
        net.fit(iter(make_batches(4)), epochs=1)
        assert recorder.channel(CHANNEL).items() == []

    def test_profiler_never_breaks_training(self, recorder, monkeypatch):
        # telemetry must not take down the fit loop: a profiler whose
        # constructor explodes degrades to None
        monkeypatch.setattr(StepProfiler, "__init__",
                            lambda self, *a, **k: 1 / 0)
        assert step_profiler_for("train_step") is None
        net = tiny_net()
        net.fit(iter(make_batches(3)), epochs=1)      # must not raise


class TestChromeTrace:
    def _records(self):
        return [
            {"ts": 10.0, "type": "step", "program": "train_step",
             "iteration": 1, "wall_s": 0.01, "sampled": True,
             "compile": False, "depth": 2, "mfu": 0.41,
             "phases": {"etl_wait": 0.001, "h2d": 0.002,
                        "dispatch": 0.003, "device": 0.002,
                        "listener": 0.001, "forensics": 0.001,
                        "checkpoint": 0.0}},
            {"ts": 10.1, "type": "serve", "queue_wait_s": 0.004,
             "batch_form_s": 0.001, "execute_s": 0.006, "batch": 3},
            {"ts": 10.2, "type": "decode", "batch_form_s": 0.001,
             "execute_s": 0.002, "active": 2},
        ]

    def test_trace_layout_train_serve_decode_tracks(self):
        doc = chrome_trace(self._records())
        names = {e["name"] for e in doc["traceEvents"] if e["ph"] == "X"}
        assert {"etl_wait", "h2d", "dispatch", "device", "listener",
                "forensics"} <= names          # checkpoint slice was 0
        assert {"serve:queue_wait", "serve:batch_form", "serve:execute",
                "decode:batch_form", "decode:execute"} <= names
        # three processes: train, serving, generation
        procs = {e["args"]["name"] for e in doc["traceEvents"]
                 if e["ph"] == "M"}
        assert procs == {"train [train_step]", "serving", "generation"}
        # the device slice sits on its own track
        dev = [e for e in doc["traceEvents"] if e["name"] == "device"][0]
        assert dev["tid"] != [e for e in doc["traceEvents"]
                              if e["name"] == "dispatch"][0]["tid"]
        assert dev["args"]["mfu"] == 0.41

    def test_dump_load_roundtrip_and_corruption_detected(self, tmp_path):
        path = dump_chrome_trace(directory=str(tmp_path),
                                 records=self._records())
        doc = load_chrome_trace(path)
        assert doc["otherData"]["format"].startswith("dl4j-tpu-stepprof")
        # corrupt one byte inside traceEvents -> checksum must catch it
        raw = open(path).read()
        broken = raw.replace('"dispatch"', '"dispatchX"', 1)
        bad = tmp_path / "bad.json"
        bad.write_text(broken)
        with pytest.raises(ValueError, match="checksum mismatch"):
            load_chrome_trace(str(bad))
        # a non-artifact JSON is rejected up front
        notrace = tmp_path / "plain.json"
        notrace.write_text("{}")
        with pytest.raises(ValueError, match="not a stepprof trace"):
            load_chrome_trace(str(notrace))


class TestServingSlices:
    def test_serving_engine_contributes_serve_slices(self, recorder):
        from deeplearning4j_tpu.serving import ServingEngine
        net = tiny_net()
        eng = ServingEngine(net, max_batch_size=8, queue_limit=64)
        try:
            eng.warmup()
            x = np.random.default_rng(0).standard_normal((3, 4)) \
                .astype(np.float32)
            eng.predict(x)
        finally:
            eng.shutdown()
        serves = [r for r in recorder.channel(CHANNEL).items()
                  if r["type"] == "serve"]
        assert serves
        for r in serves:
            assert r["queue_wait_s"] >= 0
            assert r["batch_form_s"] >= 0
            assert r["execute_s"] > 0
            assert r["batch"] >= 1

    def test_generation_engine_contributes_prefill_decode_slices(
            self, recorder):
        from deeplearning4j_tpu.generation import (GenerationConfig,
                                                   GenerationEngine)
        from deeplearning4j_tpu.models import TransformerLM
        lm = TransformerLM(vocab_size=13, seq_len=16, embed=8,
                           n_layers=1, n_heads=2).init()
        eng = GenerationEngine.for_model(
            lm, GenerationConfig(max_slots=2, max_seq=16))
        try:
            eng.generate([1, 2, 3], max_new_tokens=3, temperature=0.0)
        finally:
            eng.shutdown()
        items = recorder.channel(CHANNEL).items()
        prefills = [r for r in items if r["type"] == "prefill"]
        decodes = [r for r in items if r["type"] == "decode"]
        assert prefills and decodes
        assert all(r["execute_s"] > 0 for r in prefills + decodes)
        assert all(r["batch_form_s"] >= 0 for r in prefills + decodes)


class TestDebugProfileEndpoint:
    def _get(self, port, route):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{route}", timeout=10) as r:
            return r.status, json.loads(r.read())

    def test_inference_server_live_view_and_dump(self, recorder,
                                                 monkeypatch):
        monkeypatch.setenv("DL4J_TPU_STEPPROF_SAMPLE", "2")
        from deeplearning4j_tpu.serving.inference_server import \
            InferenceServer
        net = tiny_net()
        net.fit(iter(make_batches(4)), epochs=1)
        srv = InferenceServer(net).start()
        try:
            status, body = self._get(srv.port, "/debug/profile")
            assert status == 200 and body["enabled"] is True
            assert len(body["records"]) == 4
            assert body["summary"]["steps"] >= 3   # compile step excluded
            assert set(body["summary"]["phase_share"]) == set(PHASES)
            status, dump = self._get(srv.port, "/debug/profile?dump=1")
            assert status == 200 and dump["ok"] is True
            loaded = load_chrome_trace(dump["path"])   # checksum-verified
            assert loaded["otherData"]["records"] == 4
        finally:
            srv.stop()

    def test_nn_server_route_and_503_without_recorder(self, recorder):
        from deeplearning4j_tpu.serving.nn_server import \
            NearestNeighborsServer
        pts = np.random.default_rng(0).standard_normal((16, 4)) \
            .astype(np.float32)
        srv = NearestNeighborsServer(pts).start()
        try:
            status, body = self._get(srv.port, "/debug/profile")
            assert status == 200 and body["enabled"] is True
            prev = set_flight_recorder(None)
            try:
                with pytest.raises(urllib.error.HTTPError) as ei:
                    self._get(srv.port, "/debug/profile")
                assert ei.value.code == 503
            finally:
                set_flight_recorder(prev)
        finally:
            srv.stop()


class TestMfuRegressionDetector:
    def test_observe_mfu_fires_below_floor_of_peak(self):
        mon = HealthMonitor(HealthConfig(mfu_warmup=3, mfu_floor_ratio=0.5,
                                         ewma_alpha=0.6))
        dets = []
        for step in range(8):
            dets += mon.observe_mfu(0.40, program="p", step=step)
        assert dets == []                       # steady at peak: silent
        for step in range(8, 20):
            dets += mon.observe_mfu(0.05, program="p", step=step)
        kinds = {d.kind for d in dets}
        assert kinds == {"mfu_regression"}
        assert any("[p]" in d.reason for d in dets)

    def test_observe_mfu_ignores_garbage(self):
        mon = HealthMonitor(HealthConfig())
        assert mon.observe_mfu(None) == []
        assert mon.observe_mfu(float("nan")) == []
        assert mon.observe_mfu(-1.0) == []

    def test_fit_feeds_detector_through_fence(self, recorder, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_STEPPROF_PROGRAM", "train_step[dense]")
        monkeypatch.setenv("DL4J_TPU_STEPPROF_SAMPLE", "2")
        monkeypatch.setenv("DL4J_TPU_PEAK_FLOPS", "1e12")
        seen = []
        mon = HealthMonitor(HealthConfig(mfu_warmup=1))
        real = mon.observe_mfu
        mon.observe_mfu = lambda *a, **k: seen.append(a) or real(*a, **k)
        prof = step_profiler_for("train_step", monitor=mon)
        assert prof is not None
        net = tiny_net()
        net._stepprof = None
        # drive the profiler through the real protocol with the injected
        # monitor (fit() builds its own profiler, which would use the
        # process-global monitor)
        from deeplearning4j_tpu.observability.clock import monotonic_s
        import jax.numpy as jnp
        for i, (x, y) in enumerate(make_batches(4)):
            prof.begin(monotonic_s())
            prof.dispatched(jnp.asarray(x).sum())
            prof.end(i)
        assert len(seen) == 2                   # one per fence
        assert all(v[0] > 0 for v in seen)


class TestStepprofCli:
    def test_cli_emits_table_and_checksummed_trace(self, tmp_path,
                                                   monkeypatch, capsys):
        import tools.stepprof as cli
        rc = cli.main(["--steps", "8", "--epochs", "1", "--sample", "2",
                       "--out", str(tmp_path)])
        assert rc == 0
        out = capsys.readouterr().out
        assert "phase" in out and "dispatch" in out
        assert "sampled coverage" in out
        tail = json.loads(out.strip().splitlines()[-1])
        assert tail["program"] == "train_step[dense]"
        assert tail["steps"] == 7               # compile step excluded
        doc = load_chrome_trace(tail["trace"])  # checksum-verified
        assert doc["otherData"]["records"] == 8
