"""Native C++ kernel tests: build, bind, and verify numerics against the
pure-Python fallbacks and the jitted device codecs (reference test model:
the cuDNN-vs-builtin validation pattern, ``ValidateCudnnLSTM``-style)."""
import numpy as np
import pytest

from deeplearning4j_tpu.utils import native
from deeplearning4j_tpu.utils.native import (available, bitmap_decode_native,
                                             bitmap_encode_native,
                                             decode_cifar, parse_csv,
                                             threshold_decode_native,
                                             threshold_encode_native,
                                             u8_to_f32)


def test_native_library_builds():
    # the toolchain is part of this environment: the native path must be live
    assert available(), "g++ build of native/dl4j_tpu_native.cpp failed"


class TestThresholdCodec:
    def test_roundtrip_reconstructs(self):
        rng = np.random.default_rng(0)
        g = rng.standard_normal(2048).astype(np.float32) * 0.01
        g[[5, 99, 1000]] = [0.5, -0.8, 0.3]
        idx, signs, residual = threshold_encode_native(g, 0.1)
        assert set(idx) == {5, 99, 1000}
        dec = threshold_decode_native(idx, signs, 0.1, g.size)
        np.testing.assert_allclose(dec + residual, g, atol=1e-6)

    def test_topk_cap(self):
        g = np.zeros(64, np.float32)
        g[:6] = [1, -2, 3, -4, 5, -6]
        idx, signs, residual = threshold_encode_native(g, 0.5, max_k=3)
        assert set(idx) == {3, 4, 5}
        assert list(signs) == [-1, 1, -1]
        dec = threshold_decode_native(idx, signs, 0.5, 64)
        np.testing.assert_allclose(dec + residual, g, atol=1e-6)

    def test_matches_jitted_device_codec(self):
        from deeplearning4j_tpu.parallel.accumulation import (
            threshold_decode, threshold_encode)
        rng = np.random.default_rng(1)
        g = rng.standard_normal(512).astype(np.float32)
        msg, res_dev = threshold_encode(g, 0.7)
        idx, signs, res_nat = threshold_encode_native(g, 0.7)
        assert set(msg["idx"]) == set(idx)
        np.testing.assert_allclose(np.asarray(res_dev), res_nat, atol=1e-6)

    def test_matches_python_fallback(self, monkeypatch):
        rng = np.random.default_rng(2)
        g = rng.standard_normal(300).astype(np.float32)
        idx_n, signs_n, res_n = threshold_encode_native(g, 0.5)
        monkeypatch.setattr(native, "_lib", None)
        monkeypatch.setattr(native, "_tried", True)
        idx_p, signs_p, res_p = threshold_encode_native(g, 0.5)
        np.testing.assert_array_equal(idx_n, idx_p)
        np.testing.assert_array_equal(signs_n, signs_p)
        np.testing.assert_allclose(res_n, res_p, atol=1e-6)


class TestBitmapCodec:
    def test_roundtrip(self):
        rng = np.random.default_rng(3)
        g = rng.standard_normal(1001).astype(np.float32)
        packed, residual = bitmap_encode_native(g, 0.5)
        assert packed.nbytes == (1001 + 3) // 4
        dec = bitmap_decode_native(packed, 0.5, 1001)
        np.testing.assert_allclose(dec + residual, g, atol=1e-6)

    def test_matches_python_fallback(self, monkeypatch):
        rng = np.random.default_rng(4)
        g = rng.standard_normal(257).astype(np.float32)
        p_n, r_n = bitmap_encode_native(g, 0.3)
        monkeypatch.setattr(native, "_lib", None)
        monkeypatch.setattr(native, "_tried", True)
        p_p, r_p = bitmap_encode_native(g, 0.3)
        np.testing.assert_array_equal(p_n, p_p)
        np.testing.assert_allclose(r_n, r_p, atol=1e-6)


class TestDecode:
    def test_u8_scale(self):
        data = np.arange(256, dtype=np.uint8)
        out = u8_to_f32(data)
        np.testing.assert_allclose(out, data / 255.0, rtol=1e-6)

    def test_cifar_decode_matches_numpy(self):
        rng = np.random.default_rng(5)
        n = 7
        rec = np.empty((n, 3073), np.uint8)
        rec[:, 0] = rng.integers(0, 10, n)
        rec[:, 1:] = rng.integers(0, 256, (n, 3072))
        labels, images = decode_cifar(rec.tobytes())
        assert images.shape == (n, 32, 32, 3)
        np.testing.assert_array_equal(labels, rec[:, 0])
        chw = rec[:, 1:].reshape(n, 3, 32, 32)
        np.testing.assert_allclose(
            images, chw.transpose(0, 2, 3, 1) / 255.0, rtol=1e-6)

    def test_cifar_bad_length(self):
        with pytest.raises(ValueError, match="3073"):
            decode_cifar(b"\x00" * 100)


class TestCsvParse:
    def test_parse_basic(self):
        out = parse_csv(b"1.5,2.5\n3.0,4.0\n")
        np.testing.assert_allclose(out, [[1.5, 2.5], [3.0, 4.0]])

    def test_parse_no_trailing_newline_and_crlf(self):
        out = parse_csv(b"1,2\r\n3,4")
        np.testing.assert_allclose(out, [[1, 2], [3, 4]])

    def test_parse_scientific_and_negative(self):
        out = parse_csv(b"-1e-3,2.5e2\n0.0,-4\n")
        np.testing.assert_allclose(out, [[-0.001, 250.0], [0.0, -4.0]])

    def test_ragged_raises(self):
        with pytest.raises(ValueError):
            parse_csv(b"1,2\n3\n")

    def test_strictness_matches_fallback(self, monkeypatch):
        # both paths must accept/reject the SAME inputs
        cases = [b"1,,3\n", b"1 2\n3 4\n", b"1, \n2,3\n", b"a,b\n",
                 b"1, 2\n 3 ,4\n", b""]
        native_results = []
        for c in cases:
            try:
                native_results.append(parse_csv(c).tolist())
            except ValueError:
                native_results.append("raise")
        monkeypatch.setattr(native, "_lib", None)
        monkeypatch.setattr(native, "_tried", True)
        for c, expect in zip(cases, native_results):
            try:
                got = parse_csv(c).tolist()
            except ValueError:
                got = "raise"
            assert got == expect, (c, got, expect)

    def test_matches_python_fallback(self, monkeypatch):
        text = b"1.25,2\n-3,4.75\n"
        a = parse_csv(text)
        monkeypatch.setattr(native, "_lib", None)
        monkeypatch.setattr(native, "_tried", True)
        b = parse_csv(text)
        np.testing.assert_array_equal(a, b)


class TestHostEncodingHandler:
    def test_host_backend_matches_device(self):
        from deeplearning4j_tpu.parallel.accumulation import EncodingHandler
        rng = np.random.default_rng(6)
        g = rng.standard_normal(1024).astype(np.float32) * 0.05
        dev = EncodingHandler(initial_threshold=0.02, decay=1.0, boost=1.0)
        host = EncodingHandler(initial_threshold=0.02, decay=1.0, boost=1.0,
                               backend="host")
        m1, m2 = dev.encode_update(g), host.encode_update(g)
        assert m1["kind"] == m2["kind"]
        if m1["kind"] == "threshold":
            assert set(m1["idx"]) == set(m2["idx"])
        np.testing.assert_allclose(np.asarray(dev.residual),
                                   np.asarray(host.residual), atol=1e-6)

    def test_bad_backend(self):
        from deeplearning4j_tpu.parallel.accumulation import EncodingHandler
        with pytest.raises(ValueError, match="backend"):
            EncodingHandler(backend="gpu")


class TestCorpusIndexer:
    """dl4j_index_corpus — the DataVec/libnd4j data-loader role: tokenize +
    vocab-index natively with EXACT str.split semantics (the bulk-emission
    oracle in test_nlp additionally pins end-to-end training equivalence)."""

    VOCAB = {"the": 0, "quick": 1, "brown": 2, "fox": 3, "jumps": 4,
             "over": 5, "lazy": 6, "dog": 7}

    def test_matches_str_split_semantics(self):
        from deeplearning4j_tpu.utils import native
        if not native.available():
            pytest.skip("no native toolchain")
        sentences = ["the quick brown fox", "jumps over  the lazy dog",
                     "", "   ", "oov words here the", "\tthe\nquick\r"]
        arrs = native.index_corpus(sentences, self.VOCAB)
        assert arrs is not None
        g = self.VOCAB.get
        for a, s in zip(arrs, sentences):
            expect = [g(t) for t in s.split() if g(t) is not None]
            assert a.tolist() == expect, (s, a.tolist(), expect)

    def test_unicode_whitespace_bails_to_python(self):
        from deeplearning4j_tpu.utils import native
        if not native.available():
            pytest.skip("no native toolchain")
        # ideographic space U+3000 and NBSP are str.split separators the
        # native path must refuse rather than mis-tokenize
        assert native.index_corpus(["a　b"], self.VOCAB) is None
        assert native.index_corpus(["a b"], self.VOCAB) is None
        # ordinary multibyte text without unicode spaces is fine
        arrs = native.index_corpus(["the 快 fox"], self.VOCAB)
        assert arrs is not None and arrs[0].tolist() == [0, 3]

    def test_word2vec_training_identical_across_paths(self, monkeypatch):
        from deeplearning4j_tpu.nlp.word2vec import Word2Vec
        from deeplearning4j_tpu.nlp import sequence_vectors as SV
        from deeplearning4j_tpu.utils import native
        if not native.available():
            pytest.skip("no native toolchain")
        sents = ["the quick brown fox jumps", "over the lazy dog the fox"] * 30

        def fit(native_on):
            w = Word2Vec(sentences=sents, layer_size=16, window=3,
                         negative=3, epochs=2, seed=5, min_word_frequency=1)
            if not native_on:
                monkeypatch.setattr(type(w), "_raw_sentences",
                                    lambda self: None)
            w.fit()
            monkeypatch.undo()
            return np.asarray(w.lookup_table.syn0)

        used = []
        orig = SV.SequenceVectors._try_native_index

        def spy(self, index_map):
            out = orig(self, index_map)
            used.append(out is not None)
            return out

        monkeypatch.setattr(SV.SequenceVectors, "_try_native_index", spy)
        a = fit(True)
        assert used and used[0], "native path was not taken"
        b = fit(False)
        np.testing.assert_array_equal(a, b)
